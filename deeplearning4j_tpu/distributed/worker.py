"""Elastic worker runtime: the per-process session against the
coordinator, and the distributed data-plane step the engines' fit loops
route through under ``conf.distributed(...)`` (docs/DISTRIBUTED.md).

Per global batch the worker computes gradients on ITS shard (sliced by
``(rank, world)`` of the current generation), all-reduces the flat
gradient + score through the coordinator barrier, and applies the
weighted-mean gradient through the engine's own updater step — so every
worker holds bit-identical params/updater state after every committed
step, and the committed trajectory equals a single-host run over the
same global batches (weighted shard-mean == global mean for the
mean-reduction losses; parity pinned ≤1e-6 in tests/test_distributed*).

Elasticity falls out of the generation protocol:

* a **generation roll** mid-step (:class:`GenerationRolled`) makes the
  survivors recompute the SAME global step with their new shard bounds
  — the committed gradient always covers the whole global batch, so a
  2→1 resize changes nothing about the trajectory;
* an **absorbed** worker (fresh join or respawned process) restores the
  in-memory snapshot the lowest-ranked survivor uploaded (params +
  updater flat vectors — the reshape-tolerant checkpoint format, so the
  restore redistributes onto the joiner's own local mesh), then its
  fit() replay-skips the already-trained prefix exactly like a
  checkpoint resume;
* an **evicted** zombie (heartbeats lost while the step loop lived) is
  fenced by the coordinator, re-admits through the breaker, and resyncs
  from the snapshot (within the current epoch).

Fault sites: ``dist.worker`` (before each local gradient compute — a
``kill`` here is a worker dying mid-epoch) and ``dist.heartbeat``
(inside the heartbeat loop — a ``kill`` makes a zombie whose lease
lapses).  See docs/RESILIENCE.md.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.ops import helpers as prec_helpers
from deeplearning4j_tpu.ops import quantize as qz
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import TransientError

log = logging.getLogger(__name__)


class GenerationRolled(Exception):
    """Internal control-flow signal: the cluster rolled to a new
    generation while this step was in flight — recompute the shard
    under the new placement (same global step)."""


class WorkerEvictedError(RuntimeError):
    """This worker was declared dead by the coordinator (lease + grace
    lapsed) while it was still running — it must re-admit and resync
    before contributing again."""


class ClusterFormationError(RuntimeError):
    """The cluster never formed / this worker never became active
    within the deadline."""


def shard_bounds(n: int, world: int, rank: int) -> Tuple[int, int]:
    """Contiguous near-equal row split of a global batch: worker
    ``rank`` of ``world`` owns rows ``[n*rank//world, n*(rank+1)//world)``
    — covers every row exactly once for any world size."""
    world = max(1, int(world))
    return (n * rank) // world, (n * (rank + 1)) // world


class DistSession:
    """One worker's membership in the elastic cluster.  ``coordinator``
    is either a :class:`~deeplearning4j_tpu.distributed.coordinator.
    Coordinator` (thread-mode tests / the dl4j-check scenario) or a
    :class:`~deeplearning4j_tpu.distributed.rpc.CoordinatorClient`
    (real multi-process clusters) — identical surface."""

    def __init__(self, coordinator, worker_id: str,
                 heartbeat_ms: float = 250.0,
                 form_timeout_s: float = 120.0,
                 rejoin: bool = True):
        self.coordinator = coordinator
        self.worker_id = str(worker_id)
        self.heartbeat_s = max(0.01, float(heartbeat_ms) / 1e3)
        self.form_timeout_s = float(form_timeout_s)
        self.rejoin = bool(rejoin)
        self.closed = False
        self.pending_skip = 0
        #: persistent error-feedback residual for the quantized-gradient
        #: tier (ops/quantize.ErrorFeedback) — lives on the session so
        #: it survives across steps but dies with the membership
        self.grad_ef: Optional[qz.ErrorFeedback] = None
        self._generation = 0
        self._rank = -1
        self._world = 0
        self._await_snapshot = False
        self._join_step = 0
        self._evicted = threading.Event()
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._model_ref = None

    # ------------------------------------------------------------------
    # Membership
    # ------------------------------------------------------------------
    def connect(self) -> dict:
        """Join (retrying past coordinator races and breaker refusals),
        start heartbeating, and — when the cluster has not trained yet —
        activate immediately.  A join into a running cluster defers
        activation until :meth:`resume_position` restores the state
        snapshot inside fit()."""
        deadline = time.monotonic() + self.form_timeout_s
        while True:
            try:
                resp = self.coordinator.join(self.worker_id)
            except TransientError:
                resp = None
            if resp is not None and resp.get("admitted"):
                break
            if time.monotonic() > deadline:
                raise ClusterFormationError(
                    f"worker {self.worker_id}: not admitted within "
                    f"{self.form_timeout_s}s (last: {resp})")
            time.sleep(min(1.0, float((resp or {}).get(
                "retry_after_s", 0.2))))
        self._await_snapshot = bool(resp.get("await_snapshot"))
        self._join_step = int(resp.get("step", 0))
        self._start_heartbeat()
        if not self._await_snapshot:
            self._note_placement(self.coordinator.sync_done(self.worker_id))
        return resp

    def _start_heartbeat(self) -> None:
        self._evicted.clear()
        self._stop.clear()
        t = threading.Thread(target=self._hb_loop, daemon=True,
                             name=f"dist-hb:{self.worker_id}")
        self._hb_thread = t
        t.start()

    def _hb_loop(self) -> None:
        try:
            while not self._stop.wait(self.heartbeat_s):
                faults.check("dist.heartbeat")
                try:
                    resp = self.coordinator.heartbeat(
                        self.worker_id, self._generation)
                except TransientError:
                    continue     # coordinator blip: the lease covers it
                if resp.get("evicted"):
                    self._evicted.set()
                    return
        except BaseException as e:  # incl. ThreadKill chaos: the lease
            # now lapses and the coordinator will declare this worker
            # dead — exactly the zombie failure mode under test
            try:
                events.emit("dist.heartbeat_lost", severity="error",
                            worker=self.worker_id,
                            error=f"{type(e).__name__}: {e}")
            except Exception:
                pass

    def heartbeat_alive(self) -> bool:
        t = self._hb_thread
        return t is not None and t.is_alive()

    def placement_tuple(self) -> Tuple[int, int, int]:
        """(generation, rank, world) — refreshed from the coordinator
        until this worker is an active member of a formed generation."""
        deadline = time.monotonic() + self.form_timeout_s
        while True:
            if self._generation > 0 and self._rank >= 0:
                return self._generation, self._rank, self._world
            out = self.coordinator.placement(self.worker_id)
            self._note_placement(out)
            if self._generation > 0 and self._rank >= 0:
                return self._generation, self._rank, self._world
            if out.get("state") == "dead":
                raise WorkerEvictedError(
                    f"worker {self.worker_id} evicted while waiting "
                    "for placement")
            if time.monotonic() > deadline:
                raise ClusterFormationError(
                    f"worker {self.worker_id}: no active placement "
                    f"within {self.form_timeout_s}s ({out})")
            time.sleep(0.02)

    def _note_placement(self, out: dict) -> None:
        if not out:
            return
        self._generation = int(out.get("generation", self._generation))
        self._world = int(out.get("world", self._world))
        if "rank" in out:
            self._rank = int(out["rank"])

    # ------------------------------------------------------------------
    # Data plane
    # ------------------------------------------------------------------
    def before_step(self, iteration: int) -> None:
        """Pre-dispatch hook: the ``dist.worker`` fault site, plus
        eviction fast-path (the heartbeat thread saw it first)."""
        faults.check("dist.worker")
        if self._evicted.is_set():
            raise WorkerEvictedError(
                f"worker {self.worker_id} evicted (lease lapsed) at "
                f"iteration {iteration}")

    def allreduce_step(self, step: int, vec, weight: float,
                       scales=None) -> dict:
        """Contribute to global step ``step`` and block for the reduced
        result.  ``scales`` marks ``vec`` as int8 block codes (the
        quantized-gradient tier); dense f32 contributions leave it None.
        Raises :class:`GenerationRolled` when membership changed
        mid-barrier (recompute), :class:`WorkerEvictedError` when this
        worker was fenced out for good."""
        while True:
            try:
                resp = self.coordinator.allreduce(
                    self.worker_id, self._generation, step,
                    float(weight), vec, scales)
            except TransientError:
                time.sleep(0.05)
                continue
            if resp.get("evicted"):
                self._evicted.set()
                raise WorkerEvictedError(
                    f"worker {self.worker_id} evicted at step {step}")
            if resp.get("stale_step"):
                # fenced behind the cluster's committed step: this
                # worker must resync from a snapshot, not recompute
                self._note_placement(resp)
                raise WorkerEvictedError(
                    f"worker {self.worker_id} desynced at step {step} "
                    f"(cluster committed {resp.get('committed')})")
            if resp.get("rolled") or resp.get("timeout"):
                self._note_placement(resp)
                if resp.get("state") == "dead":
                    self._evicted.set()
                    raise WorkerEvictedError(
                        f"worker {self.worker_id} fenced dead at step "
                        f"{step}")
                raise GenerationRolled(
                    f"generation rolled to {self._generation} during "
                    f"step {step}")
            return resp

    # ------------------------------------------------------------------
    # State snapshot (absorption / resync)
    # ------------------------------------------------------------------
    def resume_position(self, model, skip_epochs: int,
                        skip_batches: int) -> Tuple[int, int]:
        """fit()'s dist-resume hook (runs right after the checkpoint
        auto-resume): a joiner into a running cluster waits for the
        survivors' state snapshot, restores it in place (params +
        updater redistributed onto this worker's own mesh by the
        flat-vector path), activates, and returns the replay-skip
        position — same contract as ``checkpoint.maybe_auto_resume``."""
        if not self._await_snapshot:
            return skip_epochs, skip_batches
        # the coordinator activates this worker ATOMICALLY with snapshot
        # availability (the cluster's committed step freezes at the
        # restored step), so no separate sync_done follows the restore
        snap = self._wait_snapshot(self._join_step)
        self._restore_into(model, snap)
        self._await_snapshot = False
        self._note_placement(self.coordinator.placement(self.worker_id))
        meta = snap.get("meta") or {}
        return (int(meta.get("epoch") or 0),
                int(meta.get("iteration_in_epoch") or 0))

    def _wait_snapshot(self, min_step: int) -> dict:
        deadline = time.monotonic() + self.form_timeout_s
        while True:
            snap = self.coordinator.get_snapshot(self.worker_id,
                                                 min_step=min_step)
            if snap is not None:
                return snap
            if time.monotonic() > deadline:
                raise ClusterFormationError(
                    f"worker {self.worker_id}: no state snapshot at/after "
                    f"step {min_step} within {self.form_timeout_s}s")
            time.sleep(0.02)

    def _restore_into(self, model, snap: dict) -> None:
        from deeplearning4j_tpu.nn import checkpoint as ckpt_mod
        with monitor.span("dist/restore", phase="reshard"):
            model.set_params(np.asarray(snap["params"], np.float32))
            upd = snap.get("updater")
            if upd is not None and np.asarray(upd).size:
                model.set_updater_state_flat(np.asarray(upd, np.float32))
        meta = snap.get("meta") or {}
        model.iteration = int(snap.get("step") or 0)
        model.epoch = int(meta.get("epoch") or 0)
        ckpt_mod._fast_forward_rng(model)
        events.emit("dist.snapshot_restored", worker=self.worker_id,
                    step=model.iteration, epoch=model.epoch)

    def upload_snapshot(self, model) -> None:
        """Lowest-ranked survivor's side of absorption: post-step state
        relay through the coordinator."""
        params = np.asarray(model.params(), np.float32)
        upd = np.asarray(model.updater_state_flat(), np.float32)
        meta = {"epoch": int(getattr(model, "epoch", 0)),
                "iteration_in_epoch": int(
                    model.iteration
                    - int(getattr(model, "_epoch_start_iter", 0) or 0))}
        self.coordinator.put_snapshot(
            self.worker_id, int(model.iteration), params,
            upd if upd.size else None, meta)

    def rejoin_and_resync(self, model) -> None:
        """Zombie recovery: re-admit through the breaker, restore the
        freshest snapshot, re-activate.  ``model.iteration`` lands on
        the snapshot's committed step; the caller turns the delta into
        a replay-skip of the stream (same-epoch resync)."""
        self._stop.set()          # retire any still-running heartbeat
        deadline = time.monotonic() + self.form_timeout_s
        while True:
            try:
                resp = self.coordinator.join(self.worker_id)
            except TransientError:
                resp = None
            if resp is not None and resp.get("admitted"):
                break
            if time.monotonic() > deadline:
                raise WorkerEvictedError(
                    f"worker {self.worker_id}: re-admission refused "
                    f"within {self.form_timeout_s}s (last: {resp})")
            time.sleep(min(1.0, float((resp or {}).get(
                "retry_after_s", 0.1))))
        self._start_heartbeat()
        if resp.get("await_snapshot"):
            # activation rides the snapshot (see resume_position)
            snap = self._wait_snapshot(int(resp.get("step", 0)))
            self._restore_into(model, snap)
            self._note_placement(self.coordinator.placement(self.worker_id))
        else:
            self._note_placement(self.coordinator.sync_done(self.worker_id))

    # ------------------------------------------------------------------
    def attach(self, model) -> None:
        self._model_ref = weakref.ref(model)

    def close(self, leave: bool = True) -> None:
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(2.0)
        if leave:
            try:
                model = (self._model_ref() if self._model_ref is not None
                         else None)
                if model is not None:
                    # leave the final committed state behind: a worker
                    # respawned AFTER the survivors finish still absorbs
                    # (restores this snapshot, replay-skips the whole
                    # stream) instead of timing out against an empty
                    # cluster
                    self.upload_snapshot(model)
            except Exception:
                pass   # best-effort: departure must not hang
            try:
                self.coordinator.leave(self.worker_id)
            except Exception:
                pass   # coordinator already gone: nothing to leave


# ----------------------------------------------------------------------
# The engines' distributed step (routed from MLN/CG _fit_batch)
# ----------------------------------------------------------------------
def _dist_fns(model) -> dict:
    """Per-model jitted halves of the distributed step: the gradient
    fn (same loss closure as the fused step — ``_build_grad_raw``) and
    the apply fn (the engine's ``_apply_updates``, donated buffers).
    Cached on the model; ``_check_trace_token`` invalidates."""
    fns = getattr(model, "_dist_cache", None)
    if fns is None:
        grad_raw = model._build_grad_raw()

        def apply_fn(p, o, gr, it):
            return model._apply_updates(p, o, gr, it)

        fns = {"grad": jax.jit(grad_raw),
               "apply": jax.jit(apply_fn, donate_argnums=(0, 1))}  # dl4j: noqa[DL4J104] one jit per model, cached in model._dist_cache
        model._dist_cache = fns
    return fns


def _flatten_leaves(tree) -> np.ndarray:
    """Host-gathered flat float32 vector over a pytree's leaves (per-
    leaf ``np.asarray``: correct for mixed committed shardings — see
    nn/params.flatten)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate(
        [np.ravel(np.asarray(l)).astype(np.float32) for l in leaves])


def _unflatten_like(flat: np.ndarray, tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out, off = [], 0
    for l in leaves:
        n = int(np.prod(l.shape)) if l.shape else 1
        out.append(jnp.asarray(
            np.asarray(flat[off:off + n]).reshape(l.shape), l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _slice_batch(ds, lo: int, hi: int, is_graph: bool):
    """(xs, ys, fms, lms) host arrays for this worker's shard rows."""
    def cut(a):
        return None if a is None else np.asarray(a)[lo:hi]
    if is_graph:
        return (tuple(cut(f) for f in ds.features),
                tuple(cut(l) for l in ds.labels),
                (None if ds.features_masks is None
                 else tuple(cut(m) for m in ds.features_masks)),
                (None if ds.labels_masks is None
                 else tuple(cut(m) for m in ds.labels_masks)))
    return (cut(ds.features), cut(ds.labels),
            cut(ds.features_mask), cut(ds.labels_mask))


def fit_batch(model, ds, sess: DistSession, is_graph: bool) -> None:
    """ONE global train step through the cluster: shard-local gradients
    → coordinator barrier all-reduce → engine updater apply.  Handles
    generation rolls (recompute same step under the new world) and
    eviction (rejoin + snapshot resync + replay-skip) in place, so the
    surrounding fit loop stays the engines' ordinary epoch/batch
    loop."""
    if sess.pending_skip > 0:
        # stream resync after an in-fit snapshot restore: consume the
        # already-trained batch without stepping
        sess.pending_skip -= 1
        return
    n = ds.num_examples()
    fns = _dist_fns(model)
    step_target = model.iteration + 1
    t_step = time.perf_counter()
    try:
        resp, new_states = _barrier_step(model, ds, sess, is_graph, fns,
                                         step_target, n)
    except BaseException:
        # a dying worker (ThreadKill chaos, a real crash) must stop
        # heartbeating so the cluster evicts it promptly instead of
        # waiting out a zombie lease
        sess.close(leave=False)
        raise
    if resp is None:
        return   # consumed as part of a post-resync replay-skip
    reduced = np.asarray(resp["vec"], np.float32)
    with monitor.span("fit/step", phase="dist_apply"):
        grads_tree = _unflatten_like(reduced[1:], model.net_params)
        model.net_params, model.opt_states = fns["apply"](
            model.net_params, model.opt_states, grads_tree,
            jnp.asarray(model.iteration, jnp.int32))
    model.net_state = new_states
    model._strip_rnn_state()
    model._score = float(reduced[0])
    model.iteration += 1
    model.last_batch_size = n
    monitor.record_fit_step(n, time.perf_counter() - t_step,
                            float(reduced[0]))
    with monitor.span("fit/step", phase="listeners"):
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration)
    if resp.get("upload_state"):
        # a joiner is waiting: relay post-step state (absorption)
        with monitor.span("dist/snapshot", phase="upload"):
            sess.upload_snapshot(model)


def _grad_quant_on(model) -> bool:
    """Whether this worker's barrier contribution goes int8.  Conf
    opt-in (``dist_grad_quant``) composes with the precision-tier
    registry: ``DL4J_DIST_QUANT=0`` kills it fleet-wide, ``=1`` forces
    it on, and the warm self-test must pass once per process (a failure
    disables the tier and the worker falls back to dense f32 — the
    coordinator accepts both, so a partial rollout still trains)."""
    mode = getattr(model.conf.global_conf, "dist_grad_quant", None)
    return bool(prec_helpers.precision_enabled("grad_quant", mode)
                and prec_helpers.ensure_precision_validated("grad_quant"))


def _barrier_step(model, ds, sess: DistSession, is_graph: bool,
                  fns: dict, step_target: int, n: int):
    """Shard-compute + barrier for ONE global step, retrying across
    generation rolls and resyncing across evictions.  Returns
    ``(reduce response, local new_states)`` — or ``(None, None)`` when
    the batch was consumed by a replay-skip after a resync.

    Under the quantized-gradient tier the contribution is int8 block
    codes + per-block scales with a persistent error-feedback residual:
    the residual is folded in BEFORE quantizing, committed only once the
    barrier ACCEPTS the contribution, and reset whenever the generation
    rolls or this worker resyncs (the shard it compensated for no longer
    exists).  The raw f32 score rides as ``scales[0]`` — unquantized."""
    while True:
        try:
            with monitor.span("fit/step", phase="dist_barrier"):
                sess.before_step(model.iteration)
                gen, rank, world = sess.placement_tuple()
            lo, hi = shard_bounds(n, world, rank)
            with monitor.span("fit/step", phase="jit_call"):
                xs, ys, fms, lms = _slice_batch(ds, lo, hi, is_graph)
                model._key, sub = jax.random.split(model._key)
                score, new_states, grads = fns["grad"](
                    model.net_params, model.net_state, xs, ys, fms, lms,
                    sub)
                flat = _flatten_leaves(grads)
            quant = _grad_quant_on(model)
            if quant:
                if sess.grad_ef is None:
                    sess.grad_ef = qz.ErrorFeedback()
                comp, codes, bscales = sess.grad_ef.compensate(flat)
                payload = codes
                wire_scales = np.concatenate(
                    [np.asarray([score], np.float32), bscales])
            else:
                payload = np.concatenate(
                    [np.asarray([score], np.float32), flat])
                wire_scales = None
            with monitor.span("fit/step", phase="dist_barrier"):
                resp = sess.allreduce_step(step_target, payload,
                                           weight=hi - lo,
                                           scales=wire_scales)
            if quant:
                # the barrier accepted this contribution: the residual
                # becomes what the quantizer dropped this step
                sess.grad_ef.commit(comp, codes, bscales)
                qz.record_grad_bytes(
                    "int8", payload.nbytes + wire_scales.nbytes)
            else:
                qz.record_grad_bytes("float32", payload.nbytes)
            return resp, new_states
        except GenerationRolled:
            if sess.grad_ef is not None:
                sess.grad_ef.reset("generation_rolled")
            continue     # same step, new shard bounds
        except WorkerEvictedError:
            if sess.grad_ef is not None:
                sess.grad_ef.reset("evicted")
            if not sess.rejoin:
                raise
            before = model.iteration
            sess.rejoin_and_resync(model)
            extra = model.iteration - before
            if extra > 0:
                # the cluster committed `extra` steps while this worker
                # was fenced out; this batch is the first of them
                sess.pending_skip = extra - 1
                return None, None
            step_target = model.iteration + 1
            continue


# ----------------------------------------------------------------------
# Session wiring for conf-driven fit() (the launcher's env contract)
# ----------------------------------------------------------------------
_STATE = {"session": None}
ENV_COORDINATOR = "DL4J_DIST_COORDINATOR"
ENV_WORKER_ID = "DL4J_DIST_WORKER_ID"
ENV_EXPECTED = "DL4J_DIST_EXPECTED"


def install_session(sess: Optional[DistSession]) -> None:
    """Make ``sess`` the process-wide session fit() attaches (tests and
    embedders; the launcher path goes through the env vars)."""
    _STATE["session"] = sess


def active_session() -> Optional[DistSession]:
    s = _STATE["session"]
    return None if (s is None or s.closed) else s


def maybe_session(g) -> Optional[DistSession]:
    """fit()'s hook: the active session for a ``dist_enabled`` conf, or
    None (single-process: conf is inert, replica semantics — the same
    graceful degrade as ``conf.sharding``).  Lazily connects from the
    conf/env coordinator address the launcher exports."""
    if not getattr(g, "dist_enabled", False):
        return None
    s = active_session()
    if s is not None:
        return s
    addr = (getattr(g, "dist_coordinator", None)
            or os.environ.get(ENV_COORDINATOR))
    if not addr:
        return None
    from deeplearning4j_tpu.distributed.rpc import CoordinatorClient
    worker_id = os.environ.get(ENV_WORKER_ID) or f"w-pid{os.getpid()}"
    sess = DistSession(
        CoordinatorClient(addr), worker_id,
        heartbeat_ms=float(getattr(g, "dist_heartbeat_ms", 250.0)))
    sess.connect()
    install_session(sess)
    return sess


def shutdown_session(leave: bool = True) -> None:
    s = _STATE["session"]
    _STATE["session"] = None
    if s is not None:
        s.close(leave=leave)
