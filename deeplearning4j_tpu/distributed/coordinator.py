"""Elastic cluster coordinator — membership, leases, generation-numbered
cluster epochs, and the step barrier/all-reduce of the coordinator data
plane (docs/DISTRIBUTED.md).

The modern equivalent of the reference's Spark ``TrainingMaster`` driver
(ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java): workers
register here, renew a **lease** by heartbeating, and drive training
through a per-step **barrier + weighted all-reduce** of their gradient
contributions.  Membership is versioned by a **generation** number: every
visible membership change (a worker dying, a worker being absorbed) rolls
the cluster to a new generation with freshly assigned ranks, and every
data-plane call is *fenced* by the generation it was made under — a stale
worker's step is rejected, never silently merged (arXiv 2112.01075's
redistribution model: state moves at epoch boundaries, the collective
itself is portable across cluster shapes).

Worker lifecycle (the dl4j-check spec machine,
``analysis/check/specs.WorkerLifecycleSpec``)::

    (join) -> joined -> active -> suspect -> dead
                ^         ^---------'          |
                '------- rejoin --------------'

* ``joined``  — admitted, syncing state (not counted in the barrier);
* ``active``  — barrier-participating member of the current generation;
* ``suspect`` — lease expired (missed heartbeats); recovers to active on
  the next heartbeat, or
* ``dead``    — suspect past the grace window: evicted, breaker charged,
  generation rolled so the survivors continue without it.

Re-admission goes through a per-worker :class:`CircuitBreaker` — a
flapping worker (repeated quick deaths) is refused with a retry-after
instead of thrashing the cluster with generation rolls.

The class is transport-agnostic and thread-safe (one condition variable;
timed waits + an injectable ``clock`` keep it deterministic under the
dl4j-check harness).  ``distributed/rpc.py`` serves it over HTTP.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.monitor.registry import get_registry
from deeplearning4j_tpu.resilience.errors import CircuitOpenError
from deeplearning4j_tpu.resilience.policy import CircuitBreaker

JOINED, ACTIVE, SUSPECT, DEAD = "joined", "active", "suspect", "dead"


class Member:
    """One registered worker: identity, lifecycle state, lease."""

    __slots__ = ("id", "state", "lease_deadline", "join_seq", "rank",
                 "restarts")

    def __init__(self, worker_id: str, join_seq: int, lease_deadline: float):
        self.id = worker_id
        self.state = JOINED
        self.lease_deadline = lease_deadline
        self.join_seq = join_seq
        self.rank = -1
        self.restarts = 0

    def to_dict(self) -> dict:
        return {"id": self.id, "state": self.state, "rank": self.rank,
                "restarts": self.restarts}


class Coordinator:
    """Membership registry + generation epochs + the step all-reduce.

    ``expected`` gates INITIAL formation only: generation 1 is rolled
    once that many workers have joined and activated (an elastic resize
    later never waits for a count).  ``lease_ms`` is the heartbeat
    lease; a member whose lease lapses turns ``suspect`` and, after
    ``suspect_grace_ms`` more, ``dead`` — which rolls the generation so
    the survivors' next barrier completes without it.  ``clock`` is
    injectable (tests, the dl4j-check scenario) so liveness decisions
    are a pure function of the driven time."""

    def __init__(self, expected: int = 0, lease_ms: float = 2000.0,
                 suspect_grace_ms: Optional[float] = None,
                 allreduce_timeout_s: float = 120.0,
                 breaker: Optional[dict] = None,
                 clock=time.monotonic):
        self.expected = max(0, int(expected))
        self.lease_s = max(0.01, float(lease_ms) / 1e3)
        self.suspect_grace_s = (self.lease_s if suspect_grace_ms is None
                                else max(0.0, float(suspect_grace_ms) / 1e3))
        self.allreduce_timeout_s = float(allreduce_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._members: Dict[str, Member] = {}
        self._join_seq = 0
        self.generation = 0
        self.step = 0                      # last COMMITTED global step
        #: in-flight contributions for step ``self.step + 1`` of the
        #: current generation: worker_id -> (weight, float64 vector)
        self._contrib: Dict[str, tuple] = {}
        #: completed reductions: step -> {"vec", "weight", "generation"}
        self._done: Dict[int, dict] = {}
        self._snapshot: Optional[dict] = None
        self._snapshot_wanted = False
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_conf = dict(breaker or {})
        self._breaker_conf.setdefault("failure_threshold", 0.5)
        self._breaker_conf.setdefault("window", 4)
        self._breaker_conf.setdefault("min_calls", 2)
        self._breaker_conf.setdefault("cooldown_s", 2.0)
        self.closed = False
        reg = get_registry()
        self._g_generation = reg.gauge(
            "dl4j_dist_generation",
            "current cluster generation (bumped on every membership "
            "change)")
        self._g_members = reg.gauge(
            "dl4j_dist_members", "cluster members by lifecycle state",
            labels=("state",))
        self._c_rolls = reg.counter(
            "dl4j_dist_generation_rolls_total",
            "generation rolls by trigger", labels=("reason",))
        self._c_allreduce = reg.counter(
            "dl4j_dist_allreduce_total",
            "step all-reduce calls by outcome (ok / rolled / fenced)",
            labels=("outcome",))
        self._h_allreduce = reg.histogram(
            "dl4j_dist_allreduce_seconds",
            "barrier + reduce wall time per completed step")
        self._c_evictions = reg.counter(
            "dl4j_dist_evictions_total",
            "workers declared dead after their lease and grace lapsed")
        self._c_rejoins = reg.counter(
            "dl4j_dist_rejoins_total",
            "workers re-admitted after an earlier death/eviction")
        self._c_snapshots = reg.counter(
            "dl4j_dist_snapshot_transfers_total",
            "in-memory state snapshots relayed to absorbing workers")
        self._g_generation.set(0)

    # ------------------------------------------------------------------
    # Internals (call with the lock held)
    # ------------------------------------------------------------------
    def _breaker_for(self, worker_id: str) -> CircuitBreaker:
        br = self._breakers.get(worker_id)
        if br is None:
            br = CircuitBreaker(name=f"dist-admit:{worker_id}",
                                clock=self._clock, **self._breaker_conf)
            self._breakers[worker_id] = br
        return br

    def _gauges_locked(self) -> None:
        counts = {JOINED: 0, ACTIVE: 0, SUSPECT: 0}
        for m in self._members.values():
            counts[m.state] = counts.get(m.state, 0) + 1
        for state, n in counts.items():
            self._g_members.labels(state=state).set(n)
        self._g_generation.set(self.generation)

    def _active_locked(self) -> List[Member]:
        out = [m for m in self._members.values()
               if m.state in (ACTIVE, SUSPECT)]
        out.sort(key=lambda m: m.join_seq)
        return out

    def _roll_locked(self, reason: str) -> None:
        """Start a new generation: re-rank the live members, discard the
        in-flight barrier (contributors will be told to recompute), and
        wake every waiter."""
        self.generation += 1
        for rank, m in enumerate(self._active_locked()):
            m.rank = rank
        self._contrib.clear()
        self._c_rolls.labels(reason=reason).inc()
        self._gauges_locked()
        events.emit("dist.generation_rolled", severity="warn",
                    generation=self.generation, reason=reason,
                    world=len(self._active_locked()))
        self._cond.notify_all()

    def _sweep_locked(self) -> None:
        """Lease accounting: expired leases turn members suspect, and a
        suspect past the grace window dies — charging its admission
        breaker and rolling the generation."""
        now = self._clock()
        rolled = False
        for m in list(self._members.values()):
            if m.state in (ACTIVE, JOINED) and now > m.lease_deadline:
                m.state = SUSPECT
                events.emit("dist.worker_suspect", severity="warn",
                            worker=m.id, generation=self.generation)
            if (m.state == SUSPECT
                    and now > m.lease_deadline + self.suspect_grace_s):
                m.state = DEAD
                del self._members[m.id]
                self._breaker_for(m.id).record(False)
                self._c_evictions.inc()
                events.emit("dist.worker_dead", severity="error",
                            worker=m.id, generation=self.generation)
                rolled = True
        if rolled:
            self._roll_locked("worker_dead")
        else:
            self._gauges_locked()

    def _placement_locked(self, worker_id: Optional[str] = None) -> dict:
        active = self._active_locked()
        out = {"generation": self.generation, "world": len(active),
               "step": self.step,
               "snapshot_wanted": self._snapshot_wanted,
               "members": [m.id for m in active]}
        if worker_id is not None:
            m = self._members.get(worker_id)
            out["rank"] = m.rank if m is not None else -1
            out["state"] = m.state if m is not None else DEAD
        return out

    # ------------------------------------------------------------------
    # Membership RPCs
    # ------------------------------------------------------------------
    def join(self, worker_id: str) -> dict:
        """Admit a worker (through its admission breaker) into the
        ``joined`` (syncing) state.  A worker re-using the id of a
        still-listed member replaces it — the old incarnation is a
        zombie by definition.  Returns admission + whether the joiner
        must await a state snapshot before activating (training already
        under way)."""
        with self._lock:
            self._sweep_locked()
            br = self._breaker_for(worker_id)
            try:
                br.acquire()
            except CircuitOpenError as e:
                return {"admitted": False,
                        "retry_after_s": float(e.retry_after_s),
                        "reason": "breaker_open"}
            rejoin = False
            old = self._members.get(worker_id)
            if old is not None:
                # a replacement for a zombie incarnation: evict the old
                # one now rather than waiting out its lease
                del self._members[worker_id]
                events.emit("dist.worker_dead", severity="warn",
                            worker=worker_id,
                            generation=self.generation, replaced=True)
                self._roll_locked("worker_replaced")
                rejoin = True
            if br.state != CircuitBreaker.CLOSED or self._was_dead(worker_id):
                rejoin = True
            self._join_seq += 1
            m = Member(worker_id, self._join_seq,
                       self._clock() + self.lease_s)
            self._members[worker_id] = m
            if rejoin:
                self._c_rejoins.inc()
            await_snapshot = self.step > 0
            if await_snapshot:
                self._snapshot_wanted = True
            events.emit("dist.worker_joined", worker=worker_id,
                        generation=self.generation, rejoin=rejoin)
            self._gauges_locked()
            self._cond.notify_all()
            return {"admitted": True, "await_snapshot": await_snapshot,
                    **self._placement_locked(worker_id)}

    def _was_dead(self, worker_id: str) -> bool:
        br = self._breakers.get(worker_id)
        if br is None:
            return False
        snap = br.snapshot()
        return bool(snap["window_failures"]) or snap["state"] != "closed"

    def sync_done(self, worker_id: str) -> dict:
        """A joined worker finished syncing state (restored the snapshot
        or had nothing to restore): promote it to ``active``.  During
        initial formation the roll to generation 1 waits for
        ``expected`` active workers; afterwards every activation rolls
        immediately — absorption is a membership change."""
        with self._lock:
            m = self._members.get(worker_id)
            if m is None:
                return {"evicted": True}
            m.state = ACTIVE
            m.lease_deadline = self._clock() + self.lease_s
            self._breaker_for(worker_id).record(True)
            events.emit("dist.worker_active", worker=worker_id,
                        generation=self.generation)
            if self.generation == 0:
                n_active = sum(1 for x in self._members.values()
                               if x.state == ACTIVE)
                if n_active >= max(1, self.expected):
                    self._roll_locked("formation")
            else:
                self._roll_locked("worker_absorbed")
            self._gauges_locked()
            return self._placement_locked(worker_id)

    def heartbeat(self, worker_id: str, generation: int = -1) -> dict:
        """Renew a worker's lease.  The response doubles as the
        out-of-band control channel: current generation (so a worker
        learns of a roll between steps), eviction notice, and the
        snapshot-upload request for the lowest-ranked member."""
        with self._lock:
            self._sweep_locked()
            m = self._members.get(worker_id)
            if m is None:
                return {"evicted": True}
            m.lease_deadline = self._clock() + self.lease_s
            if m.state == SUSPECT:
                m.state = ACTIVE if m.rank >= 0 else JOINED
                events.emit("dist.worker_active", worker=worker_id,
                            generation=self.generation, recovered=True)
                self._gauges_locked()
            return {"generation": self.generation, "step": self.step,
                    "upload_state": self._upload_wanted_locked(m)}

    def leave(self, worker_id: str) -> dict:
        """Graceful departure (end of script): no breaker charge, but
        the survivors still roll to a new generation."""
        with self._lock:
            m = self._members.pop(worker_id, None)
            if m is not None:
                events.emit("dist.worker_dead", worker=worker_id,
                            generation=self.generation, graceful=True)
                self._roll_locked("worker_left")
            return {"left": m is not None}

    def placement(self, worker_id: Optional[str] = None) -> dict:
        with self._lock:
            self._sweep_locked()
            return self._placement_locked(worker_id)

    # ------------------------------------------------------------------
    # Data plane: the step barrier + weighted all-reduce
    # ------------------------------------------------------------------
    def _upload_wanted_locked(self, m: Member) -> bool:
        if not self._snapshot_wanted or m.state != ACTIVE:
            return False
        active = self._active_locked()
        return bool(active) and active[0].id == m.id

    @staticmethod
    def _contribution_f64(vec, scales) -> np.ndarray:
        """A contribution as the float64 vector the rank-order reduce
        accumulates.  A quantized contribution arrives as int8 block
        codes in ``vec`` plus ``scales`` = [raw f32 score, per-block
        scales...] (the ops/quantize wire shape); dequantization is
        exact (int8 × f32 is representable in f32), so int8 and f32
        contributors share one bit-stable accumulation order — mixed
        fleets interoperate, the npy wire dtype says which is which."""
        arr = np.asarray(vec)
        if scales is None or arr.dtype != np.int8:
            return np.asarray(vec, np.float64).ravel()
        from deeplearning4j_tpu.ops import quantize as qz
        s = np.asarray(scales, np.float32).ravel()
        grads = qz.dequantize_blocks(arr.ravel(), s[1:])
        return np.concatenate([s[:1].astype(np.float64),
                               grads.astype(np.float64)])

    def allreduce(self, worker_id: str, generation: int, step: int,
                  weight: float, vec, scales=None) -> dict:
        """One worker's contribution to global step ``step`` (must be
        the next uncommitted step).  Blocks until every active member of
        the CURRENT generation has contributed, then returns the
        weighted mean (float64 accumulation in rank order — bit-stable
        across runs).  ``scales`` marks an int8-quantized contribution
        (see :meth:`_contribution_f64`); it is dequantized here, at
        admission, so the barrier and reduce below never see dtypes.
        If the generation rolls while waiting (a peer died, a peer was
        absorbed), returns ``{"rolled": True}`` with the fresh placement
        and the caller recomputes its shard under the new world."""
        t0 = time.perf_counter()
        vec64 = self._contribution_f64(vec, scales)
        with self._lock:
            self._sweep_locked()
            m = self._members.get(worker_id)
            if m is None:
                self._c_allreduce.labels(outcome="fenced").inc()
                return {"evicted": True}
            if self.generation == 0:
                # still forming: there is no data plane yet — a partial
                # membership must never complete a barrier
                self._c_allreduce.labels(outcome="fenced").inc()
                return {"rolled": True,
                        **self._placement_locked(worker_id)}
            # a SUSPECT member may still contribute (its shard is still
            # assigned to it until death) — only the heartbeat channel
            # renews the lease, so a truly dead worker still ages out
            if generation != self.generation \
                    or m.state not in (ACTIVE, SUSPECT):
                self._c_allreduce.labels(outcome="fenced").inc()
                events.emit("dist.step_fenced", severity="warn",
                            worker=worker_id, generation=generation,
                            step=step)
                return {"rolled": True,
                        **self._placement_locked(worker_id)}
            if self.step == 0 and not self._done and step > 1:
                # a freshly started coordinator meeting workers that
                # resumed from a checkpoint: adopt their position (every
                # worker restores the same manifest, so the first
                # contribution names the cluster's committed step)
                self.step = step - 1
            if step != self.step + 1:
                # a desynced worker (zombie resubmitting a committed
                # step, or one that skipped ahead): fence it out — it
                # must resync, never merge
                self._c_allreduce.labels(outcome="fenced").inc()
                events.emit("dist.step_fenced", severity="warn",
                            worker=worker_id, generation=generation,
                            step=step, committed=self.step)
                return {"stale_step": True, "committed": self.step,
                        **self._placement_locked(worker_id)}
            entry_gen = self.generation
            self._contrib[worker_id] = (float(weight), vec64)
            self._maybe_reduce_locked()
            deadline = time.monotonic() + self.allreduce_timeout_s
            while True:
                done = self._done.get(step)
                if done is not None and done["generation"] == entry_gen:
                    self._c_allreduce.labels(outcome="ok").inc()
                    self._h_allreduce.observe(time.perf_counter() - t0)
                    return {"vec": done["vec"], "weight": done["weight"],
                            "step": step, "generation": entry_gen,
                            "upload_state": self._upload_wanted_locked(m)}
                if self.generation != entry_gen:
                    self._c_allreduce.labels(outcome="rolled").inc()
                    return {"rolled": True,
                            **self._placement_locked(worker_id)}
                if time.monotonic() > deadline:
                    self._c_allreduce.labels(outcome="timeout").inc()
                    self._contrib.pop(worker_id, None)
                    return {"timeout": True,
                            **self._placement_locked(worker_id)}
                # short slices so lease expiry of a dead peer is noticed
                # by the waiters themselves (no background reaper)
                self._cond.wait(min(0.05, self.lease_s / 4))
                self._sweep_locked()

    def _maybe_reduce_locked(self) -> None:
        """Complete the barrier when every RANKED member (active or
        momentarily suspect — a suspect still owns its batch shard until
        it is declared dead) has contributed: weighted sum in rank order
        (float64) over the total weight."""
        ready = self._active_locked()
        if not ready or any(m.id not in self._contrib for m in ready):
            return
        total_w = sum(self._contrib[m.id][0] for m in ready)
        acc = None
        for m in ready:                      # rank order: bit-stable
            w, v = self._contrib[m.id]
            acc = w * v if acc is None else acc + w * v
        vec = (acc / total_w if total_w > 0 else acc).astype(np.float32)
        step = self.step + 1
        self._done[step] = {"vec": vec, "weight": total_w,
                            "generation": self.generation}
        for old in [s for s in self._done if s < step - 2]:
            del self._done[old]
        self.step = step
        self._contrib.clear()
        self._cond.notify_all()

    # ------------------------------------------------------------------
    # State snapshot relay (absorbing a worker without a checkpoint)
    # ------------------------------------------------------------------
    def _activate_joiners_locked(self) -> None:
        """Promote every syncing (JOINED) member to ACTIVE and roll —
        called ATOMICALLY with snapshot availability so the cluster's
        committed step freezes at exactly the step the joiners restore:
        the survivors' next barrier includes them, and their first
        contribution (snapshot step + 1) is the cluster's next step.
        Without this atomicity a joiner restores state the survivors
        have already trained past (the stale-restore deadlock)."""
        absorbed = [m for m in self._members.values()
                    if m.state == JOINED]
        if not absorbed:
            return
        for m in absorbed:
            m.state = ACTIVE
            self._breaker_for(m.id).record(True)
            events.emit("dist.worker_active", worker=m.id,
                        generation=self.generation, absorbed=True)
        self._roll_locked("worker_absorbed")

    def put_snapshot(self, worker_id: str, step: int, params,
                     updater, meta: Optional[dict] = None) -> dict:
        """The lowest-ranked survivor uploads its post-step state; the
        coordinator relays it to syncing joiners (in-memory absorption —
        the restore side redistributes it onto the joiner's own mesh
        through the reshape-tolerant flat-vector path) and activates
        them in the same locked operation (see
        :meth:`_activate_joiners_locked`)."""
        params = np.asarray(params, np.float32)
        updater = (None if updater is None
                   else np.asarray(updater, np.float32))
        with self._lock:
            self._snapshot = {"step": int(step), "params": params,
                              "updater": updater,
                              "meta": dict(meta or {}),
                              "from": worker_id}
            self._snapshot_wanted = False
            self._c_snapshots.inc()
            events.emit("dist.snapshot_transferred", worker=worker_id,
                        step=int(step),
                        bytes=int(params.nbytes
                                  + (updater.nbytes
                                     if updater is not None else 0)))
            if int(step) >= self.step:
                self._activate_joiners_locked()
            self._cond.notify_all()
            return {"stored": True}

    def get_snapshot(self, worker_id: str,
                     min_step: int = 0) -> Optional[dict]:
        """The joiner's poll.  Returns the stored snapshot only while it
        matches the cluster's CURRENT committed step (and ``min_step``)
        — and, for a still-syncing caller, activates it in the same
        locked read, so restore-and-continue is race-free against the
        survivors' stepping.  Otherwise records that a fresh snapshot is
        wanted (the next barrier response asks rank 0 to upload) and
        returns None."""
        with self._lock:
            self._sweep_locked()
            m = self._members.get(worker_id)
            if m is not None:
                m.lease_deadline = self._clock() + self.lease_s
            snap = self._snapshot
            if snap is not None and snap["step"] >= int(min_step) \
                    and snap["step"] >= self.step:
                if m is not None and m.state == JOINED:
                    self._activate_joiners_locked()
                return snap
            self._snapshot_wanted = True
            return None

    # ------------------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            self._sweep_locked()
            return {"generation": self.generation, "step": self.step,
                    "expected": self.expected,
                    "members": [m.to_dict() for m in sorted(
                        self._members.values(), key=lambda m: m.join_seq)],
                    "snapshot_step": (self._snapshot or {}).get("step"),
                    "breakers": {k: b.snapshot()
                                 for k, b in self._breakers.items()}}

    def close(self) -> None:
        with self._lock:
            self.closed = True
            self._cond.notify_all()
