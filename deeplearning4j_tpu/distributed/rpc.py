"""HTTP transport for the elastic coordinator — the same JSON-RPC wire
shape as ``server/gateway.Server`` (``POST / {"method", "params"}``),
with gradient/param vectors shipped as base64 ``.npy`` payloads (the
bit-exact binary codec the fleet tier's carry migration uses).

``CoordinatorServer`` wraps a :class:`Coordinator` in a
``ThreadingHTTPServer`` (one blocked barrier call per worker rides one
handler thread); ``CoordinatorClient`` is the worker-side stub, mapping
connection-level failures onto :class:`TransientError` so the worker's
retry policies compose (docs/RESILIENCE.md)."""

from __future__ import annotations

import base64
import io
import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.resilience.errors import TransientError


class CoordinatorUnavailableError(TransientError):
    """The coordinator could not be reached (refused / reset / timed
    out) — retryable; the cluster is useless without it, so workers
    retry rather than fail over."""


def encode_vec(vec) -> Optional[str]:
    """vector → base64 ``.npy`` (bit-exact round trip).  The npy header
    carries the dtype on the wire, which is what lets mixed fleets
    interoperate: int8 arrays (quantized gradient codes — see
    ops/quantize) ship as-is at 1/4 the bytes, every other dtype is
    coerced to float32 exactly as before."""
    if vec is None:
        return None
    arr = np.asarray(vec)
    if arr.dtype != np.int8:
        arr = arr.astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_vec(blob: Optional[str]):
    if blob is None:
        return None
    buf = io.BytesIO(base64.b64decode(blob.encode("ascii")))
    return np.load(buf, allow_pickle=False)


#: request/response fields carried as binary npy instead of JSON lists
#: ("scales" = the quantized contribution's [score, per-block scales])
_VEC_FIELDS = ("vec", "params", "updater", "scales")


def _pack(doc: dict) -> dict:
    out = dict(doc)
    for k in _VEC_FIELDS:
        if out.get(k) is not None:
            out[k] = encode_vec(out[k])
    return out


def _unpack(doc: dict) -> dict:
    out = dict(doc)
    for k in _VEC_FIELDS:
        if out.get(k) is not None:
            out[k] = decode_vec(out[k])
    return out


class CoordinatorServer:
    """Serves a :class:`Coordinator` over localhost-grade HTTP.  The
    method surface mirrors the class one-to-one; ``GET /healthz`` and
    ``GET /status`` are bare probe surfaces for the launcher."""

    METHODS = ("join", "sync_done", "heartbeat", "leave", "placement",
               "allreduce", "put_snapshot", "get_snapshot", "status")

    def __init__(self, coordinator, host: str = "127.0.0.1",
                 port: int = 0):
        self.coordinator = coordinator
        server = self

        class Handler(BaseHTTPRequestHandler):
            daemon_threads = True

            def log_message(self, *a):            # quiet
                pass

            def _reply(self, code: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    self._reply(200, {"ok": True})
                    return
                if self.path.startswith("/status"):
                    self._reply(200, server.coordinator.status())
                    return
                self._reply(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    method = doc.get("method")
                    if method not in CoordinatorServer.METHODS:
                        self._reply(400, {"error":
                                          f"unknown method {method!r}"})
                        return
                    params = _unpack(doc.get("params") or {})
                    result = getattr(server.coordinator, method)(**params)
                    self._reply(200, {"result": _pack(result or {})})
                except Exception as e:  # malformed frame / codec error
                    self._reply(500,
                                {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "CoordinatorServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="dist-coordinator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self.coordinator.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(5.0)


class CoordinatorClient:
    """Worker-side stub speaking the wire protocol above.  Exposes the
    same method surface as :class:`Coordinator` so
    ``distributed.worker.DistSession`` runs identically against an
    in-process coordinator object (thread-mode tests) or this client
    (real multi-process clusters)."""

    def __init__(self, base_url: str, timeout_s: float = 180.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def __repr__(self):
        return f"CoordinatorClient({self.base_url!r})"

    def _call(self, method: str, timeout_s: Optional[float] = None,
              **params) -> dict:
        body = json.dumps({"method": method,
                           "params": _pack(params)}).encode()
        req = urllib.request.Request(
            self.base_url + "/", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as r:
                doc = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read() or b"{}").get("error")
            except Exception:
                msg = None
            raise RuntimeError(f"coordinator {method} failed: "
                               f"{msg or f'HTTP {e.code}'}") from None
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise CoordinatorUnavailableError(
                f"coordinator {self.base_url} unreachable for "
                f"{method!r}: {getattr(e, 'reason', e)}") from None
        return _unpack(doc.get("result") or {})

    # -- the Coordinator surface --------------------------------------
    def join(self, worker_id):
        return self._call("join", worker_id=worker_id)

    def sync_done(self, worker_id):
        return self._call("sync_done", worker_id=worker_id)

    def heartbeat(self, worker_id, generation=-1):
        return self._call("heartbeat", timeout_s=10.0,
                          worker_id=worker_id, generation=generation)

    def leave(self, worker_id):
        return self._call("leave", worker_id=worker_id)

    def placement(self, worker_id=None):
        return self._call("placement", worker_id=worker_id)

    def allreduce(self, worker_id, generation, step, weight, vec,
                  scales=None):
        return self._call("allreduce", worker_id=worker_id,
                          generation=generation, step=step,
                          weight=weight, vec=vec, scales=scales)

    def put_snapshot(self, worker_id, step, params, updater, meta=None):
        return self._call("put_snapshot", worker_id=worker_id, step=step,
                          params=params, updater=updater, meta=meta)

    def get_snapshot(self, worker_id, min_step=0):
        out = self._call("get_snapshot", worker_id=worker_id,
                         min_step=min_step)
        return out or None

    def status(self):
        return self._call("status")
