"""Cross-cutting fault-tolerance layer: retry/backoff, circuit
breaking, load shedding, and deterministic fault injection.

The reference DL4J stack was built for unreliable fleets — its Spark
``TrainingMaster`` / param-averaging layer (mirrored in ``scaleout/``)
tolerates executor loss, and production serving assumes overload and
partial failure.  This package is that posture for the reproduction:

* :mod:`~deeplearning4j_tpu.resilience.policy` —
  :class:`RetryPolicy` (exponential backoff + seeded jitter, optional
  per-attempt timeout and total deadline budget) and
  :class:`CircuitBreaker` (closed/open/half-open with a failure-rate
  window and cooldown), both usable as decorators or call wrappers and
  both metered into the monitor registry
  (``dl4j_resilience_retries_total``, ``dl4j_resilience_breaker_state``).
* :mod:`~deeplearning4j_tpu.resilience.faults` — a deterministic
  fault-injection registry: named sites in the serving/input/checkpoint
  paths where a :class:`FaultPlan` (fail-on-nth-call, injected latency,
  seeded probability) can be armed via the ``DL4J_FAULT_PLAN`` env var
  or the API, so chaos tests are reproducible in CI.

Wired in: the serving gateway (admission control + ``/healthz`` /
``/readyz``), ``MicroBatcher`` (deadline shedding, dead-thread
recovery), ``ModelCache`` (retry + breaker around loads), the input
pipeline feeder (reader retries) and ``CheckpointListener`` /
``resume_from_checkpoint`` (atomic writes, corrupt-checkpoint
fallback).  Catalog + tuning guide: docs/RESILIENCE.md.
"""

from deeplearning4j_tpu.resilience.errors import (  # noqa: F401
    CircuitOpenError, CorruptCheckpointError, DeadlineExceededError,
    OverloadedError, TransientError)
from deeplearning4j_tpu.resilience.policy import (  # noqa: F401
    CircuitBreaker, RetryPolicy)
from deeplearning4j_tpu.resilience import faults  # noqa: F401
from deeplearning4j_tpu.resilience.faults import FaultPlan  # noqa: F401
