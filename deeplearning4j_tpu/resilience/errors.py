"""Exception taxonomy for the resilience layer.

The split matters operationally: ``TransientError`` (and the stdlib
transients — ``ConnectionError``, ``TimeoutError``, ``OSError``) are
what :class:`~deeplearning4j_tpu.resilience.policy.RetryPolicy` retries
by default; ``OverloadedError`` / ``CircuitOpenError`` map to HTTP 503
with ``Retry-After`` at the gateway (shed, don't queue);
``DeadlineExceededError`` maps to 504 (the client's budget is gone —
late work is wasted work)."""

from __future__ import annotations


class TransientError(RuntimeError):
    """A failure worth retrying: the operation may succeed if repeated
    (flaky reader, hiccuping filesystem, injected chaos)."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline budget expired before (or while) the work
    ran.  Shed requests see this instead of a silent hang."""


class OverloadedError(RuntimeError):
    """Admission control rejected the request: queue depth is past the
    limit.  ``retry_after_s`` is the backoff hint the gateway surfaces
    as an HTTP ``Retry-After`` header."""

    def __init__(self, message: str = "server overloaded",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class CircuitOpenError(RuntimeError):
    """The circuit breaker is open — the protected dependency has been
    failing and calls are short-circuited until the cooldown elapses.
    ``retry_after_s`` is the remaining cooldown."""

    def __init__(self, message: str = "circuit open",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class CorruptCheckpointError(RuntimeError):
    """A checkpoint zip failed validation (truncated write, bad CRC,
    unparsable config) — resume skips it and falls back to the previous
    one instead of dying on it."""
