"""Retry/backoff and circuit breaking — the two call-wrapping
resilience primitives (SURVEY §5: the reference keeps training alive on
unreliable fleets; serving assumes partial failure).

Both are usable two ways::

    policy = RetryPolicy(max_attempts=4, seed=7, name="reader")
    value = policy.call(flaky_fn, arg)          # wrapper

    @RetryPolicy(max_attempts=3)
    def load(path): ...                          # decorator

Determinism: backoff jitter comes from a private ``random.Random(seed)``
— two policies built with the same seed produce the same delay
sequence, so chaos tests (and their CI reruns) see identical timing
decisions.  Both primitives meter into the process registry:
``dl4j_resilience_retries_total{policy=}``,
``dl4j_resilience_breaker_state{breaker=}`` (0 closed / 1 half-open /
2 open) and ``dl4j_resilience_breaker_transitions_total{breaker=,to=}``.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence, Tuple, Type

from deeplearning4j_tpu.resilience.errors import (
    CircuitOpenError, TransientError)

# What a RetryPolicy retries unless told otherwise: our own transient
# marker plus the stdlib's "try again" family.  ConnectionError /
# TimeoutError / OSError cover flaky readers, sockets and filesystems;
# everything else (ValueError, a real bug) surfaces immediately.
DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (
    TransientError, ConnectionError, TimeoutError, OSError)


def _registry():
    from deeplearning4j_tpu import monitor
    return monitor.get_registry()


class RetryPolicy:
    """Exponential backoff with seeded jitter, an optional per-attempt
    timeout, and a total deadline budget.

    ``max_attempts`` counts the first try (``max_attempts=3`` = 1 try +
    2 retries).  Delay before retry ``i`` (0-based) is
    ``min(max_delay_ms, base_delay_ms * multiplier**i)`` scaled by a
    jitter factor drawn uniformly from ``[1 - jitter, 1]`` — full
    decorrelation without ever exceeding the deterministic envelope.
    ``deadline_s`` caps the whole call (attempts + sleeps): a retry that
    could not finish inside the budget is not started.
    ``attempt_timeout_s`` runs each attempt on a watchdog thread and
    treats overrun as a retryable ``TimeoutError`` (the hung attempt is
    abandoned, not interrupted — use for I/O-bound calls)."""

    def __init__(self, max_attempts: int = 3, base_delay_ms: float = 50.0,
                 max_delay_ms: float = 2000.0, multiplier: float = 2.0,
                 jitter: float = 0.5, seed: Optional[int] = None,
                 attempt_timeout_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 retry_on: Optional[Sequence[Type[BaseException]]] = None,
                 name: str = "default",
                 sleep: Callable[[float], None] = time.sleep):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = max(0.0, float(base_delay_ms)) / 1e3
        self.max_delay_s = max(self.base_delay_s, float(max_delay_ms) / 1e3)
        self.multiplier = max(1.0, float(multiplier))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self.attempt_timeout_s = attempt_timeout_s
        self.deadline_s = deadline_s
        self.retry_on = tuple(retry_on) if retry_on is not None \
            else DEFAULT_RETRY_ON
        self.name = name
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        reg = _registry()
        self._c_retries = reg.counter(
            "dl4j_resilience_retries_total",
            "retry attempts made after a failed first try",
            labels=("policy",)).labels(policy=name)
        self._c_exhausted = reg.counter(
            "dl4j_resilience_retry_exhausted_total",
            "calls that failed after exhausting every retry",
            labels=("policy",)).labels(policy=name)

    # ------------------------------------------------------------------
    def delays(self, n: Optional[int] = None):
        """The next ``n`` backoff delays (seconds) this policy would
        sleep, consuming its jitter RNG — seeded policies yield
        identical sequences (the determinism contract chaos tests pin).
        Defaults to one delay per possible retry."""
        n = self.max_attempts - 1 if n is None else int(n)
        out = []
        with self._lock:
            for i in range(n):
                d = min(self.max_delay_s,
                        self.base_delay_s * self.multiplier ** i)
                out.append(d * (1.0 - self.jitter * self._rng.random()))
        return out

    def _next_delay(self, attempt: int) -> float:
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** attempt)
        with self._lock:
            return d * (1.0 - self.jitter * self._rng.random())

    def _run_attempt(self, fn, args, kwargs):
        if self.attempt_timeout_s is None:
            return fn(*args, **kwargs)
        box = {}

        def target():
            try:
                box["value"] = fn(*args, **kwargs)
            except BaseException as e:  # delivered on the caller thread
                box["error"] = e
        t = threading.Thread(target=target, daemon=True,
                             name=f"retry-attempt:{self.name}")
        t.start()
        t.join(self.attempt_timeout_s)
        if t.is_alive():
            raise TimeoutError(
                f"attempt exceeded {self.attempt_timeout_s}s "
                f"(policy {self.name!r})")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def call(self, fn: Callable, *args, on_retry: Optional[Callable] = None,
             **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy.  ``on_retry``
        (if given) is called with ``(attempt_index, exception)`` before
        each backoff sleep — the logging/telemetry hook."""
        t_start = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return self._run_attempt(fn, args, kwargs)
            except self.retry_on as e:
                last = e
                if attempt + 1 >= self.max_attempts:
                    break
                delay = self._next_delay(attempt)
                if (self.deadline_s is not None
                        and time.monotonic() - t_start + delay
                        >= self.deadline_s):
                    break  # a retry that can't fit the budget isn't made
                if on_retry is not None:
                    on_retry(attempt, e)
                self._c_retries.inc()
                if delay > 0:
                    self._sleep(delay)
        self._c_exhausted.inc()
        assert last is not None
        raise last

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: ``@RetryPolicy(...)``."""
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapper.retry_policy = self
        return wrapper


class CircuitBreaker:
    """Closed → open → half-open breaker over a rolling failure-rate
    window.

    *Closed*: calls pass; outcomes land in a window of the last
    ``window`` calls.  Once ``min_calls`` outcomes exist and the failure
    rate reaches ``failure_threshold``, the breaker opens.
    *Open*: calls fail fast with :class:`CircuitOpenError` (carrying the
    remaining cooldown as ``retry_after_s``) for ``cooldown_s``.
    *Half-open*: after the cooldown, up to ``half_open_max`` probe
    calls are let through; a success closes the breaker (window
    cleared), a failure re-opens it and restarts the cooldown.

    ``clock`` is injectable so tests drive time instead of sleeping."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, failure_threshold: float = 0.5, window: int = 20,
                 min_calls: int = 5, cooldown_s: float = 30.0,
                 half_open_max: int = 1, name: str = "default",
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = min(1.0, max(0.0, float(failure_threshold)))
        self.window = max(1, int(window))
        self.min_calls = max(1, int(min_calls))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.half_open_max = max(1, int(half_open_max))
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: deque = deque(maxlen=self.window)
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probes_in_flight = 0
        reg = _registry()
        self._g_state = reg.gauge(
            "dl4j_resilience_breaker_state",
            "breaker state (0 closed, 1 half-open, 2 open)",
            labels=("breaker",)).labels(breaker=name)
        self._c_transitions = reg.counter(
            "dl4j_resilience_breaker_transitions_total",
            "breaker state transitions", labels=("breaker", "to"))
        self._c_short_circuited = reg.counter(
            "dl4j_resilience_breaker_short_circuited_total",
            "calls rejected while the breaker was open",
            labels=("breaker",)).labels(breaker=name)
        self._g_state.set(0)

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, to: str) -> None:
        if self._state == to:
            return
        self._state = to
        self._g_state.set(self._STATE_CODE[to])
        self._c_transitions.labels(breaker=self.name, to=to).inc()
        try:
            from deeplearning4j_tpu.monitor import events
            events.emit("breaker.transition",
                        severity="warn" if to != self.CLOSED else "info",
                        breaker=self.name, to=to)
        except Exception:
            pass  # state machines must not die on telemetry
        if to == self.OPEN:
            self._opened_at = self._clock()
        if to == self.HALF_OPEN:
            self._probes_in_flight = 0
        if to == self.CLOSED:
            self._outcomes.clear()

    def _maybe_half_open_locked(self) -> None:
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._transition_locked(self.HALF_OPEN)

    def acquire(self) -> None:
        """Gate a call: no-op when closed/half-open (with probe budget),
        raises :class:`CircuitOpenError` when open."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return
            if self._state == self.HALF_OPEN:
                if self._probes_in_flight < self.half_open_max:
                    self._probes_in_flight += 1
                    return
                remaining = 0.1  # probes saturated: come back shortly
            else:
                remaining = max(
                    0.0, self.cooldown_s - (self._clock() - self._opened_at))
            self._c_short_circuited.inc()
            raise CircuitOpenError(
                f"circuit {self.name!r} open "
                f"(retry in {remaining:.2f}s)", retry_after_s=remaining)

    def record(self, ok: bool) -> None:
        """Report a call outcome (for code that gates with
        :meth:`acquire` manually instead of using :meth:`call`)."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                self._transition_locked(
                    self.CLOSED if ok else self.OPEN)
                return
            self._outcomes.append(bool(ok))
            if self._state == self.CLOSED and not ok:
                n = len(self._outcomes)
                failures = n - sum(self._outcomes)
                if (n >= self.min_calls
                        and failures / n >= self.failure_threshold):
                    self._transition_locked(self.OPEN)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` through the breaker: fail fast when open, record
        the outcome otherwise.  ``CircuitOpenError`` raised by a NESTED
        breaker is not counted against this one's window."""
        self.acquire()
        try:
            result = fn(*args, **kwargs)
        except CircuitOpenError:
            raise
        except Exception:
            self.record(False)
            raise
        self.record(True)
        return result

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: ``@CircuitBreaker(...)``."""
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapper.circuit_breaker = self
        return wrapper

    def reset(self) -> None:
        """Force-close (ops override / test isolation)."""
        with self._lock:
            self._transition_locked(self.CLOSED)

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open_locked()
            n = len(self._outcomes)
            failures = n - sum(self._outcomes)
            return {"state": self._state, "window_calls": n,
                    "window_failures": failures,
                    "failure_rate": round(failures / n, 3) if n else 0.0}
