"""Deterministic fault injection — reproducible chaos for CI.

Production code is instrumented with named **sites**::

    reader.next_raw      input-pipeline feeder, before each raw pull
    cache.load           ModelCache, around the checkpoint load
    batcher.compute      MicroBatcher, before the jitted inference call
    checkpoint.write     CheckpointListener, before a checkpoint save
    gateway.predict      gateway entry point, on each predict request
    decode.step          DecodePool batcher, before each decode dispatch
    fleet.migrate        DecodePool batcher, before each session
                         export/import control op (a kill here is a
                         replica dying mid-migration)
    dist.worker          elastic worker, before each cluster step's
                         local gradient compute (a kill here is a
                         worker preempted mid-epoch)
    dist.heartbeat       elastic worker heartbeat loop, each tick (a
                         kill makes a zombie: the step loop lives but
                         the lease lapses and the coordinator evicts)

Each instrumented point calls :func:`check(site)`; with nothing armed
that is a single attribute read.  A :class:`FaultPlan` armed at a site
(via :func:`arm`, or the ``DL4J_FAULT_PLAN`` env var carrying one JSON
plan or a list of them) decides per call whether to inject:

* ``mode="fail"`` — raise ``exc`` (default :class:`TransientError`, so
  retry policies engage; use ``"RuntimeError"`` for a non-retryable
  crash);
* ``mode="latency"`` — sleep ``latency_ms`` (tail-latency chaos);
* ``mode="kill"`` — raise :class:`ThreadKill` (a ``BaseException`` that
  sails past ``except Exception`` handlers — how tests kill a worker
  thread deterministically).

Determinism: ``on_call=n`` fires on exactly the n-th check (1-based,
counted from arming) and ``probability=p`` draws from a
``random.Random(seed)`` private to the plan — the injection sequence is
a pure function of the plan, so a chaos test replays identically in CI.
Injections are counted in ``dl4j_resilience_faults_injected_total{site=}``.

Example ``DL4J_FAULT_PLAN``::

    [{"site": "reader.next_raw", "mode": "fail", "probability": 0.01,
      "seed": 7, "exc": "TransientError"},
     {"site": "cache.load", "mode": "latency", "latency_ms": 50,
      "probability": 0.01, "seed": 11}]
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Union

from deeplearning4j_tpu.resilience.errors import TransientError

# The instrumented sites (docs/RESILIENCE.md keeps the prose catalog).
SITES = ("reader.next_raw", "cache.load", "batcher.compute",
         "checkpoint.write", "gateway.predict", "decode.step",
         "fleet.migrate", "dist.worker", "dist.heartbeat")

ENV_VAR = "DL4J_FAULT_PLAN"


class ThreadKill(BaseException):
    """Deliberately NOT an Exception: escapes ``except Exception``
    blocks so an armed ``mode="kill"`` plan takes down the target
    thread the way a segfaulting dependency or ``kill -9``'d helper
    would — the failure the dead-thread recovery paths exist for."""


_EXC_BY_NAME = {
    "TransientError": TransientError,
    "RuntimeError": RuntimeError,
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
    "IOError": OSError,
    "ValueError": ValueError,
}


class FaultPlan:
    """One armed fault: where (``site``), what (``mode``), when
    (``on_call`` exact n-th check, and/or seeded ``probability`` per
    check), bounded by ``max_injections``."""

    def __init__(self, site: str, mode: str = "fail",
                 on_call: Optional[int] = None, probability: float = 0.0,
                 seed: int = 0, latency_ms: float = 0.0,
                 exc: str = "TransientError", message: Optional[str] = None,
                 max_injections: Optional[int] = None):
        if mode not in ("fail", "latency", "kill"):
            raise ValueError(f"unknown fault mode {mode!r}")
        if exc not in _EXC_BY_NAME:
            raise ValueError(f"unknown exc {exc!r}; one of "
                             f"{sorted(_EXC_BY_NAME)}")
        self.site = str(site)
        self.mode = mode
        self.on_call = None if on_call is None else int(on_call)
        self.probability = min(1.0, max(0.0, float(probability)))
        self.seed = int(seed)
        self.latency_ms = max(0.0, float(latency_ms))
        self.exc_name = exc
        self.message = message
        self.max_injections = (None if max_injections is None
                               else int(max_injections))
        self.injected = 0
        self._rng = random.Random(self.seed)

    @staticmethod
    def from_dict(d: dict) -> "FaultPlan":
        return FaultPlan(**d)

    def to_dict(self) -> dict:
        return {"site": self.site, "mode": self.mode,
                "on_call": self.on_call, "probability": self.probability,
                "seed": self.seed, "latency_ms": self.latency_ms,
                "exc": self.exc_name, "max_injections": self.max_injections,
                "injected": self.injected}

    def _should_inject(self, call_idx: int) -> bool:
        if (self.max_injections is not None
                and self.injected >= self.max_injections):
            return False
        if self.on_call is not None:
            return call_idx == self.on_call
        if self.probability > 0.0:
            # one deterministic draw per check, even when a prior plan
            # already injected — the sequence depends only on the seed
            # and call index, never on sibling plans
            return self._rng.random() < self.probability
        return False

    def _inject(self, site: str) -> None:
        _count_injection(site, self.mode)
        if self.mode == "latency":
            time.sleep(self.latency_ms / 1e3)
            return
        msg = self.message or (f"injected fault at {site} "
                               f"(call #{_CALLS.get(site, 0)})")
        if self.mode == "kill":
            raise ThreadKill(msg)
        raise _EXC_BY_NAME[self.exc_name](msg)


_LOCK = threading.RLock()
_PLANS: Dict[str, List[FaultPlan]] = {}
_CALLS: Dict[str, int] = {}
_ACTIVE = False          # fast-path guard: check() is one read when off
_ENV_LOADED = False


def _count_injection(site: str, mode: str) -> None:
    try:
        from deeplearning4j_tpu import monitor
        monitor.get_registry().counter(
            "dl4j_resilience_faults_injected_total",
            "faults injected by armed fault plans",
            labels=("site", "mode")).labels(site=site, mode=mode).inc()
        # journaled with the trace context of the injected call — the
        # flight-recorder dump of a chaos kill names the request it hit
        monitor.events.emit("fault.injected", severity="warn",
                            site=site, mode=mode)
    except Exception:
        pass  # chaos must not die on telemetry


def _load_env_locked() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return
    spec = json.loads(raw)
    for d in (spec if isinstance(spec, list) else [spec]):
        _arm_locked(FaultPlan.from_dict(d))


def _arm_locked(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _PLANS.setdefault(plan.site, []).append(plan)
    _CALLS.setdefault(plan.site, 0)
    _ACTIVE = True
    return plan


def arm(plan: Union[FaultPlan, dict, str]) -> FaultPlan:
    """Arm a plan (a :class:`FaultPlan`, a plan dict, or its JSON).
    Call counting at the plan's site starts at the first :func:`check`
    after arming."""
    if isinstance(plan, str):
        plan = json.loads(plan)
    if isinstance(plan, dict):
        plan = FaultPlan.from_dict(plan)
    with _LOCK:
        _load_env_locked()
        return _arm_locked(plan)


def disarm(site: Optional[str] = None) -> int:
    """Remove armed plans for ``site`` (or every site when None).
    Returns how many plans were dropped.  Call counters survive until
    :func:`reset`."""
    global _ACTIVE
    with _LOCK:
        if site is None:
            n = sum(len(v) for v in _PLANS.values())
            _PLANS.clear()
        else:
            n = len(_PLANS.pop(site, []))
        _ACTIVE = bool(_PLANS)
        return n


def reset() -> None:
    """Disarm everything and zero call counters (test isolation).  The
    env var is re-read on the next :func:`check`/:func:`arm`."""
    global _ACTIVE, _ENV_LOADED
    with _LOCK:
        _PLANS.clear()
        _CALLS.clear()
        _ACTIVE = False
        _ENV_LOADED = False


def call_count(site: str) -> int:
    with _LOCK:
        return _CALLS.get(site, 0)


def armed(site: Optional[str] = None) -> List[dict]:
    """Introspection: the armed plans (for ``site`` or all)."""
    with _LOCK:
        plans = (_PLANS.get(site, []) if site is not None
                 else [p for ps in _PLANS.values() for p in ps])
        return [p.to_dict() for p in plans]


def check(site: str) -> None:
    """The instrumentation hook.  Cheap when nothing is armed; with
    plans armed at ``site``, bumps the site's call counter and lets each
    plan (in arming order) inject — a latency plan delays and falls
    through, a fail/kill plan raises."""
    if not _ACTIVE and _ENV_LOADED:
        return
    with _LOCK:
        _load_env_locked()
        plans = _PLANS.get(site)
        if not plans:
            return
        _CALLS[site] = idx = _CALLS.get(site, 0) + 1
        due = []
        for p in plans:
            if p._should_inject(idx):
                p.injected += 1  # counted under the lock so
                due.append(p)    # max_injections can't over-fire
    for p in due:
        p._inject(site)
