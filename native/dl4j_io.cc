// dl4j_io — native host-runtime library for deeplearning4j_tpu.
//
// The reference's native tier is libnd4j (C++ math kernels) plus
// JavaCPP-bridged cuDNN/HDF5/Aeron (SURVEY.md §2.3/§2.10).  On TPU the
// math tier is XLA behind PJRT; what remains genuinely native on the
// host side is the data path — the role DataVec + AsyncDataSetIterator's
// prefetch thread play (ref: datasets/iterator/AsyncDataSetIterator.java:39-127)
// — and arena staging buffers (ref: MemoryWorkspace, nn/conf/WorkspaceMode.java).
//
// Exposed C ABI (consumed from Python via ctypes, no pybind11 in image):
//   CSV  : csv_dims / csv_read        — fast numeric CSV → float32 matrix
//   IDX  : idx_dims / idx_read        — MNIST IDX (big-endian) → float32
//   Fetch: prefetch_open/next/close   — threaded file read-ahead queue
//   Arena: arena_create/alloc/reset/destroy — 64B-aligned bump allocator
//
// Build: native/Makefile → deeplearning4j_tpu/native/libdl4j_io.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <limits>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CSV: one pass to size, one pass to fill caller-provided memory.
// Non-numeric fields parse as NaN (the transform pipeline's filter_invalid
// handles them); empty lines are skipped.

static bool read_file(const char* path, std::string* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  f.seekg(0, std::ios::end);
  out->resize(static_cast<size_t>(f.tellg()));
  f.seekg(0);
  f.read(&(*out)[0], static_cast<std::streamsize>(out->size()));
  return true;
}

int csv_dims(const char* path, char delim, int skip_lines, long* rows,
             long* cols) {
  std::string data;
  if (!read_file(path, &data)) return -1;
  long r = 0, c = 0, cur_cols = 1;
  bool in_line = false;
  int skipped = 0;
  for (size_t i = 0; i < data.size(); ++i) {
    char ch = data[i];
    if (ch == '\n') {
      if (skipped < skip_lines) {
        ++skipped;
      } else if (in_line) {
        ++r;
        if (cur_cols > c) c = cur_cols;
      }
      cur_cols = 1;
      in_line = false;
    } else if (ch == delim) {
      if (skipped >= skip_lines) ++cur_cols;
      in_line = true;
    } else if (ch != '\r' && ch != ' ' && ch != '\t') {
      // whitespace alone must not count as a data row — csv_read skips
      // blank lines, and dims/read must agree
      in_line = true;
    }
  }
  if (in_line && skipped >= skip_lines) {
    ++r;
    if (cur_cols > c) c = cur_cols;
  }
  *rows = r;
  *cols = c;
  return 0;
}

int csv_read(const char* path, char delim, int skip_lines, float* out,
             long rows, long cols) {
  std::string data;
  if (!read_file(path, &data)) return -1;
  long r = 0;
  int skipped = 0;
  size_t i = 0, n = data.size();
  while (i < n && r < rows) {
    // one line
    size_t line_end = data.find('\n', i);
    if (line_end == std::string::npos) line_end = n;
    if (skipped < skip_lines) {
      ++skipped;
      i = line_end + 1;
      continue;
    }
    // skip blank lines — same whitespace set as csv_dims ('\r', ' ', '\t'),
    // except the delimiter itself, which always marks a data row (a
    // tab-only line is blank for a comma CSV but a row of empty fields
    // for a TSV, matching csv_dims' delim-first branch)
    bool blank = true;
    for (size_t j = i; j < line_end; ++j) {
      char ch = data[j];
      if (ch != delim && (ch == '\r' || ch == ' ' || ch == '\t')) continue;
      blank = false;
      break;
    }
    if (blank) {
      i = line_end + 1;
      continue;
    }
    long c = 0;
    size_t field_start = i;
    for (size_t j = i; j <= line_end && c < cols; ++j) {
      if (j == line_end || data[j] == delim) {
        // match Python float(): whole trimmed field must parse, and hex
        // literals are rejected ('12abc' and '0x1A' are NaN both ways)
        const char* s = data.data() + field_start;
        const char* e = data.data() + j;
        while (s < e && (*s == ' ' || *s == '\t')) ++s;
        const char* trimmed_end = e;
        while (trimmed_end > s && (trimmed_end[-1] == ' ' ||
                                   trimmed_end[-1] == '\t' ||
                                   trimmed_end[-1] == '\r'))
          --trimmed_end;
        float v = std::numeric_limits<float>::quiet_NaN();
        if (trimmed_end > s) {
          const char* digits = s;
          if (*digits == '+' || *digits == '-') ++digits;  // signed hex too
          bool is_hex = (trimmed_end - digits > 1) && digits[0] == '0' &&
                        (digits[1] == 'x' || digits[1] == 'X');
          if (!is_hex) {
            char* endp = nullptr;
            float parsed = strtof(s, &endp);
            if (endp == trimmed_end) v = parsed;
          }
        }
        out[r * cols + c] = v;
        ++c;
        field_start = j + 1;
      }
    }
    for (; c < cols; ++c)
      out[r * cols + c] = std::numeric_limits<float>::quiet_NaN();
    ++r;
    i = line_end + 1;
  }
  return static_cast<int>(r);
}

// ---------------------------------------------------------------------------
// IDX (MNIST) files: magic [0, 0, dtype, ndim] then big-endian dims.

static uint32_t be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

int idx_dims(const char* path, long* ndim, long* dims /* up to 4 */) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return -1;
  unsigned char hdr[4];
  f.read(reinterpret_cast<char*>(hdr), 4);
  if (!f || hdr[0] != 0 || hdr[1] != 0) return -2;
  int nd = hdr[3];
  if (nd < 1 || nd > 4) return -3;
  *ndim = nd;
  for (int d = 0; d < nd; ++d) {
    unsigned char b[4];
    f.read(reinterpret_cast<char*>(b), 4);
    if (!f) return -4;
    dims[d] = be32(b);
  }
  return hdr[2];  // dtype code: 0x08 ubyte, 0x0D float
}

int idx_read(const char* path, float* out, long count) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return -1;
  unsigned char hdr[4];
  f.read(reinterpret_cast<char*>(hdr), 4);
  // validate the read succeeded and the magic/ndim are sane before using
  // hdr — a truncated file must not seed nd/dtype from stack garbage
  if (!f || hdr[0] != 0 || hdr[1] != 0) return -2;
  int nd = hdr[3];
  if (nd < 1 || nd > 4) return -3;
  f.seekg(4 + 4 * nd);
  if (!f) return -4;
  if (hdr[2] == 0x08) {
    std::vector<unsigned char> buf(static_cast<size_t>(count));
    f.read(reinterpret_cast<char*>(buf.data()), count);
    if (!f) return -4;
    for (long i = 0; i < count; ++i) out[i] = float(buf[i]);
  } else if (hdr[2] == 0x0D) {
    std::vector<unsigned char> buf(static_cast<size_t>(count) * 4);
    f.read(reinterpret_cast<char*>(buf.data()), count * 4);
    if (!f) return -4;
    for (long i = 0; i < count; ++i) {
      uint32_t u = be32(buf.data() + 4 * i);
      float v;
      memcpy(&v, &u, 4);
      out[i] = v;
    }
  } else {
    return -3;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Skip-gram pair generation — the word2vec windowing hot loop (the role
// of the reference's libnd4j AggregateSkipGram host-side prep).  For each
// center i, emit (context, center) index pairs over the reduced window
// [i-w+r_i, i+w-r_i], skipping self-positions and equal ids.  Caller
// provides out buffers of capacity n * 2 * window; returns pair count.

long sg_pairs(const int* ids, long n, int window, const int* reduced,
              int* ctx_out, int* ctr_out) {
  long out = 0;
  for (long i = 0; i < n; ++i) {
    int w = window - reduced[i];
    if (w <= 0) continue;
    long lo = i - w;
    if (lo < 0) lo = 0;
    long hi = i + w + 1;
    if (hi > n) hi = n;
    int center = ids[i];
    for (long c = lo; c < hi; ++c) {
      if (c == i || ids[c] == center) continue;
      ctx_out[out] = ids[c];
      ctr_out[out] = center;
      ++out;
    }
  }
  return out;
}

// ---------------------------------------------------------------------------
// Threaded file prefetcher: N reader threads pull paths from a work list
// and push (index, bytes) blobs into a bounded queue — the native
// realization of AsyncDataSetIterator's prefetch thread + BlockingQueue
// (ref: AsyncDataSetIterator.java:41).  Results are re-ordered so the
// consumer sees files in submission order.

struct Prefetcher {
  std::vector<std::string> paths;
  size_t capacity;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  // completed blobs keyed by sequence index
  std::vector<std::string*> done;
  size_t next_to_read = 0;   // next path index for workers
  size_t next_to_emit = 0;   // next index the consumer receives
  size_t buffered = 0;
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;

  ~Prefetcher() {
    stop.store(true);
    cv_put.notify_all();
    cv_get.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    for (auto* s : done) delete s;
  }

  void work() {
    for (;;) {
      size_t idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        if (stop.load() || next_to_read >= paths.size()) return;
        idx = next_to_read++;
      }
      auto* blob = new std::string();
      read_file(paths[idx].c_str(), blob);  // empty blob on failure
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] {
          return stop.load() || idx < next_to_emit + capacity;
        });
        if (stop.load()) {
          delete blob;
          return;
        }
        done[idx] = blob;
        ++buffered;
      }
      cv_get.notify_all();
    }
  }
};

void* prefetch_open(const char** paths, long n_paths, long capacity,
                    long n_threads) {
  auto* p = new Prefetcher();
  p->paths.assign(paths, paths + n_paths);
  p->capacity = static_cast<size_t>(capacity < 1 ? 1 : capacity);
  p->done.assign(p->paths.size(), nullptr);
  long nt = n_threads < 1 ? 1 : n_threads;
  for (long i = 0; i < nt; ++i)
    p->workers.emplace_back([p] { p->work(); });
  return p;
}

// Returns blob length (>=0) with *data owned by the prefetcher until the
// next call; -1 when exhausted.
long prefetch_next(void* handle, const char** data) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  if (p->next_to_emit >= p->paths.size()) return -1;
  size_t idx = p->next_to_emit;
  p->cv_get.wait(lk, [&] { return p->stop.load() || p->done[idx] != nullptr; });
  if (p->stop.load()) return -1;
  // free the previous emission
  if (idx > 0 && p->done[idx - 1] != nullptr) {
    delete p->done[idx - 1];
    p->done[idx - 1] = nullptr;
  }
  std::string* blob = p->done[idx];
  *data = blob->data();
  ++p->next_to_emit;
  --p->buffered;
  p->cv_put.notify_all();
  return static_cast<long>(blob->size());
}

void prefetch_close(void* handle) { delete static_cast<Prefetcher*>(handle); }

// ---------------------------------------------------------------------------
// Arena: 64-byte-aligned bump allocator for host staging buffers — the
// MemoryWorkspace analog (scope-based reuse, no per-batch malloc churn).

struct Arena {
  char* base;
  size_t size;
  std::atomic<size_t> offset{0};
};

void* arena_create(long bytes) {
  auto* a = new Arena();
  a->size = static_cast<size_t>(bytes);
  if (posix_memalign(reinterpret_cast<void**>(&a->base), 64, a->size) != 0) {
    delete a;
    return nullptr;
  }
  return a;
}

void* arena_alloc(void* handle, long bytes) {
  auto* a = static_cast<Arena*>(handle);
  size_t need = (static_cast<size_t>(bytes) + 63u) & ~size_t(63);
  size_t off = a->offset.fetch_add(need);
  if (off + need > a->size) {
    a->offset.fetch_sub(need);
    return nullptr;  // caller falls back to heap
  }
  return a->base + off;
}

void arena_reset(void* handle) {
  static_cast<Arena*>(handle)->offset.store(0);
}

long arena_used(void* handle) {
  return static_cast<long>(static_cast<Arena*>(handle)->offset.load());
}

void arena_destroy(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  free(a->base);
  delete a;
}

}  // extern "C"
