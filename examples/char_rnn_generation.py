"""Character-level text generation with a GravesLSTM — the
dl4j-examples ``LSTMCharModellingExample`` recipe: TBPTT training on a
text corpus, then sampling with the stateful ``rnn_time_step`` path.

Run:  python examples/char_rnn_generation.py [--platform cpu]
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse

import numpy as np

_DEFAULT_TEXT = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 40


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--segment", type=int, default=40,
                    help="TBPTT segment length")
    ap.add_argument("--hidden", type=int, default=96)
    ap.add_argument("--sample-chars", type=int, default=120)
    ap.add_argument("--text-file", default=None)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.charrnn import char_rnn

    text = (open(args.text_file).read() if args.text_file
            else _DEFAULT_TEXT)
    chars = sorted(set(text))
    V = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    eye = np.eye(V, dtype=np.float32)
    T = args.segment

    seqs = []
    for start in range(0, len(text) - T - 1, T):
        window = text[start:start + T + 1]
        seqs.append((eye[[idx[c] for c in window[:-1]]],
                     eye[[idx[c] for c in window[1:]]]))
    x = np.stack([s[0] for s in seqs])
    y = np.stack([s[1] for s in seqs])

    net = char_rnn(vocab_size=V, hidden=args.hidden, layers=2,
                   tbptt_length=T)
    net.fit(ListDataSetIterator(DataSet(x, y), 32), epochs=args.epochs)

    # sample: stateful single-step inference (rnnTimeStep semantics)
    rng = np.random.default_rng(0)
    net.rnn_clear_previous_state()
    c = text[0]
    out = [c]
    for _ in range(args.sample_chars):
        probs = np.asarray(net.rnn_time_step(
            eye[idx[c]][None, None, :]))[0, -1]
        probs = np.clip(probs, 1e-9, None)
        c = chars[rng.choice(V, p=probs / probs.sum())]
        out.append(c)
    print("generated:", "".join(out))


if __name__ == "__main__":
    main()
