"""Keras model import — the dl4j-examples modelimport recipe: save a
Keras model to HDF5, import it (config + weights), check output
equivalence, then fine-tune with this framework's one-XLA-program step.

Run:  python examples/keras_model_import.py [--platform cpu]
(Requires the ``keras`` package only for AUTHORING the .h5; importing
an existing file needs just h5py.)
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse
import tempfile
from pathlib import Path

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    try:
        import keras
        from keras import layers
    except ImportError:
        print("keras not installed — point KerasModelImport at an "
              "existing .h5 instead")
        return

    from deeplearning4j_tpu.keras_import import KerasModelImport

    km = keras.Sequential([
        layers.Input((10,)),
        layers.Dense(24, activation="relu"),
        layers.Dense(3, activation="softmax"),
    ])
    km.compile(loss="categorical_crossentropy", optimizer="sgd")

    with tempfile.TemporaryDirectory() as d:
        path = str(Path(d) / "model.h5")
        km.save(path)
        net = KerasModelImport.import_keras_sequential_model_and_weights(
            path)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 10)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               km.predict(x, verbose=0),
                               rtol=1e-4, atol=1e-5)
    print("imported model matches Keras outputs")

    from deeplearning4j_tpu.datasets.dataset import DataSet
    w = rng.normal(size=(10, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    before = float(net.score(DataSet(x, y)))
    net.fit(x, y, epochs=args.epochs)
    after = float(net.score(DataSet(x, y)))
    print(f"fine-tuned: score {before:.4f} -> {after:.4f}")


if __name__ == "__main__":
    main()
