"""VGG16 on CIFAR-10 — the BASELINE.md north-star conv/BN recipe
(dl4j-examples VGG/CIFAR training + the Keras-modelimport path).

Run:  python examples/vgg16_cifar10.py [--steps 20] [--platform cpu]

Use ``--tiny`` on CPU: the full 15-conv stack at batch 256 is a
TPU-shaped workload (bf16 MXU gemms), not a laptop one.
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--tiny", action="store_true",
                    help="batch 8 / 2 steps, for a quick CPU check")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.tiny:
        args.batch, args.steps = 8, 2

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.fetchers import load_cifar10
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.vgg import vgg16_cifar10
    from deeplearning4j_tpu.nn.listeners import PerformanceListener

    net = vgg16_cifar10()
    net.conf.global_conf.precision = "bf16"
    net.set_listeners(PerformanceListener(frequency=5))

    data = load_cifar10(train=True)
    n = min(args.batch * args.steps, data.features.shape[0])
    ds = DataSet(np.asarray(data.features[:n]), np.asarray(data.labels[:n]))
    net.fit(ListDataSetIterator(ds, args.batch), epochs=1)
    print(f"final score={float(net.score(ds.get_range(0, args.batch))):.4f}")


if __name__ == "__main__":
    main()
