"""Transfer learning — the dl4j-examples ``TransferLearning`` recipe:
train a base net, freeze its feature layers, swap the head for a new
task, fine-tune, and save/restore through the DL4J-compatible zip.

Run:  python examples/transfer_learning.py [--platform cpu]
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse
import tempfile
from pathlib import Path

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.serialization import (
        restore_multi_layer_network, write_model)
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration, TransferLearningBuilder)

    rng = np.random.default_rng(1)
    x = rng.normal(size=(128, 6)).astype(np.float32)
    w = rng.normal(size=(6, 4))
    y4 = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]

    base_conf = (NeuralNetConfiguration.builder()
                 .seed(2).learning_rate(0.05).updater("adam")
                 .list()
                 .layer(DenseLayer(n_in=6, n_out=24, activation="relu"))
                 .layer(DenseLayer(n_out=12, activation="relu"))
                 .layer(OutputLayer(n_out=4, activation="softmax",
                                    loss="mcxent"))
                 .build())
    base = MultiLayerNetwork(base_conf).init()
    base.fit(x, y4, epochs=args.epochs)
    print(f"base task score={float(base.score(DataSet(x, y4))):.4f}")

    # new 2-class task: freeze the feature layers, replace the head
    y2 = np.eye(2, dtype=np.float32)[(np.argmax(x @ w, axis=1) >= 2)
                                     .astype(int)]
    transfer = (TransferLearningBuilder(base)
                .fine_tune_configuration(FineTuneConfiguration(
                    learning_rate=0.02, updater="adam"))
                .set_feature_extractor(1)   # freeze layers 0..1
                .remove_output_layer()
                .add_layer(OutputLayer(n_in=12, n_out=2,
                                       activation="softmax", loss="mcxent"))
                .build())
    transfer.fit(x, y2, epochs=args.epochs)
    print(f"transfer task score={float(transfer.score(DataSet(x, y2))):.4f}")

    with tempfile.TemporaryDirectory() as d:
        p = str(Path(d) / "transfer.zip")
        write_model(transfer, p)
        back = restore_multi_layer_network(p)
        np.testing.assert_allclose(np.asarray(back.output(x[:4])),
                                   np.asarray(transfer.output(x[:4])),
                                   rtol=1e-5, atol=1e-6)
    print("checkpoint round-trip exact")


if __name__ == "__main__":
    main()
