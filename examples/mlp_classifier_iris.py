"""Feedforward classifier on Iris — the dl4j-examples
``IrisClassifier``/``MLPClassifier*`` recipe: builder DSL, normalizer,
train/test split, evaluation.

Run:  python examples/mlp_classifier_iris.py [--platform cpu]
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=60)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.fetchers import load_iris
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    ds = load_iris().shuffle(seed=42)
    train, test = ds.split_test_and_train(120)
    norm = NormalizerStandardize().fit(train)
    train, test = norm.transform(train), norm.transform(test)

    conf = (NeuralNetConfiguration.builder()
            .seed(6).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator(train, 30), epochs=args.epochs)
    ev = net.evaluate(ListDataSetIterator(test, 30))
    print(ev.stats())
    print(f"accuracy={ev.accuracy():.4f}")


if __name__ == "__main__":
    main()
