"""DeepWalk graph embeddings — the dl4j-examples ``DeepWalk``/graph
recipe: random walks over a graph → skip-gram on the walk sequences
(fused XLA kernels) → vertex similarity queries.

Run:  python examples/graph_deepwalk.py [--platform cpu]
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vector-size", type=int, default=16)
    ap.add_argument("--walk-length", type=int, default=20)
    ap.add_argument("--walks-per-vertex", type=int, default=8)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.graph.deepwalk import DeepWalk
    from deeplearning4j_tpu.graph.graph import Graph

    # two 8-cliques joined by a single bridge edge — embeddings should
    # recover the community structure
    g = Graph(16)
    for base in (0, 8):
        for i in range(8):
            for j in range(i + 1, 8):
                g.add_edge(base + i, base + j, directed=False)
    g.add_edge(0, 8, directed=False)

    dw = (DeepWalk.Builder()
          .vector_size(args.vector_size)
          .window_size(4)
          .walks_per_vertex(args.walks_per_vertex)
          .build())
    dw.fit_graph(g, walk_length=args.walk_length, seed=7)

    v1, v9 = str(1), str(9)
    same = dw.similarity(v1, str(2))
    cross = dw.similarity(v1, v9)
    print(f"similarity(1, 2)  [same clique]  = {same:.3f}")
    print(f"similarity(1, 9)  [cross clique] = {cross:.3f}")
    print(f"nearest(1) = {dw.words_nearest(v1, top=5)}")


if __name__ == "__main__":
    main()
