"""Word2Vec on raw text — the dl4j-examples ``Word2VecRawTextExample``
recipe: sentence iterator + tokenizer → skip-gram training (fused XLA
kernels) → nearest-word queries; optionally distributed over a worker
pool (the Spark Word2Vec tier).

Run:  python examples/word2vec_raw_text.py [--partitions 4] [--platform cpu]
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse

_SENTENCES = (
    ["the cat and the dog play together in the garden",
     "a dog chases the cat around the house",
     "my pet cat sleeps near the friendly dog",
     "the dog and cat share a pet bed"] * 25
    + ["the sun and the moon light the evening sky",
       "a bright moon rises in the clear night sky",
       "the sun warms the morning sky over the hills",
       "the moon follows the sun across the sky"] * 25)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layer-size", type=int, default=50)
    ap.add_argument("--partitions", type=int, default=1,
                    help=">1 trains distributed with parameter averaging")
    ap.add_argument("--text-file", default=None)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    sentences = ([ln.strip() for ln in open(args.text_file) if ln.strip()]
                 if args.text_file else _SENTENCES)

    if args.partitions > 1:
        from deeplearning4j_tpu.scaleout.nlp import DistributedWord2Vec
        model = DistributedWord2Vec(
            layer_size=args.layer_size, window=5, min_word_frequency=2,
            num_partitions=args.partitions, seed=42, epochs=2,
        ).fit(sentences)
    else:
        from deeplearning4j_tpu.embeddings.word2vec import Word2Vec
        from deeplearning4j_tpu.text.sentence_iterators import (
            CollectionSentenceIterator)
        builder = Word2Vec.Builder().iterate(
            CollectionSentenceIterator(sentences))
        builder.conf.layer_size = args.layer_size
        builder.conf.window = 5
        builder.conf.min_word_frequency = 2
        builder.conf.seed = 42
        model = builder.build()
        model.fit()

    for w in ("dog", "sun"):
        print(f"nearest({w}) = {model.words_nearest(w, top=5)}")
    print(f"similarity(dog, cat) = {model.similarity('dog', 'cat'):.3f}")
    print(f"similarity(dog, moon) = {model.similarity('dog', 'moon'):.3f}")


if __name__ == "__main__":
    main()
