"""Training dashboard — the dl4j-examples UI recipe: attach a
StatsListener, train, and browse the live dashboard (overview / model /
histograms / graph / flow / activations / t-SNE / system tabs, language
selector top-right).

Run:  python examples/ui_training_dashboard.py [--platform cpu]
then open the printed URL.  --seconds 0 exits immediately after
training (used by the smoke test).
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--seconds", type=float, default=600,
                    help="keep serving this long after training")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.fetchers import load_iris
    from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ui import (InMemoryStatsStorage, StatsListener,
                                       UIServer)

    storage = InMemoryStatsStorage()
    server = UIServer.get_instance()
    server.attach(storage)
    print(f"dashboard: http://{server.host}:{server.port}/")

    ds = load_iris()
    ds = NormalizerStandardize().fit(ds).transform(ds)
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id="iris-demo"))
    for _ in range(args.epochs):
        net.fit(ds)
    print(f"trained {args.epochs} epochs, score={float(net.score(ds)):.4f}")
    print("flow tab:", f"http://{server.host}:{server.port}/"
                       "#  (click Flow)")

    if args.seconds > 0:
        try:
            time.sleep(args.seconds)
        except KeyboardInterrupt:
            pass
    server.stop()


if __name__ == "__main__":
    main()
