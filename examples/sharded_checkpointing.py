"""Pod-scale checkpointing — mesh-SHARDED training state saved and
restored via Orbax: train data-parallel on the mesh, checkpoint without
a host gather, "preempt" the job, resume exactly where it stopped.
(For single-host zip-format crash recovery see nn/checkpoint.py's
CheckpointListener.)

Run (virtual 8-device CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/sharded_checkpointing.py --platform cpu
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse
import tempfile
from pathlib import Path

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.orbax_checkpoint import (load_sharded,
                                                        save_sharded)
    from deeplearning4j_tpu.parallel import (MeshConfig, ParallelWrapper,
                                             make_mesh)

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam")
                .list()
                .layer(DenseLayer(n_in=8, n_out=64, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    batches = [DataSet(x, y) for _ in range(args.steps)]

    n_dev = len(jax.devices())
    fsdp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    mesh = make_mesh(MeshConfig(data=n_dev // fsdp, fsdp=fsdp))
    print(f"mesh={dict(mesh.shape)}")

    net = build()
    pw = ParallelWrapper(net, mesh)
    pw.fit(ListDataSetIterator(list(batches)), epochs=1)
    mid_score = float(net.score(DataSet(x, y)))
    print(f"after first leg: iteration={net.iteration} "
          f"score={mid_score:.4f}")

    with tempfile.TemporaryDirectory() as d:
        ckpt = Path(d) / "ckpt"
        save_sharded(net, ckpt)   # each host writes its own shards
        print(f"saved sharded checkpoint: "
              f"{sorted(p.name for p in ckpt.iterdir())}")

        # "preemption": rebuild from disk and keep training
        resumed = load_sharded(ckpt)
        assert resumed.iteration == net.iteration
        np.testing.assert_allclose(
            np.asarray(resumed.output(x[:4])),
            np.asarray(net.output(x[:4])), rtol=1e-6)
        print(f"resumed at iteration {resumed.iteration}, outputs match")

        ParallelWrapper(resumed, mesh).fit(
            ListDataSetIterator(list(batches)), epochs=1)
        print(f"second leg done: iteration={resumed.iteration} "
              f"score={float(resumed.score(DataSet(x, y))):.4f}")


if __name__ == "__main__":
    main()
