"""Long-context training with flash attention + sequence parallelism —
the extension tier beyond the reference: a SelfAttention model whose
time dimension shards over the mesh's ``seq`` axis (ring attention /
Ulysses all-to-all), with an O(T)-memory flash kernel on TPU.

Run (virtual 8-device CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/long_context_attention.py --platform cpu
On real chips drop the env vars and raise --seq-len.
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq-degree", type=int, default=4,
                    help="size of the mesh 'seq' axis")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (RnnOutputLayer,
                                                   SelfAttentionLayer)
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.parallel import MeshConfig, make_mesh
    from deeplearning4j_tpu.parallel import sequence as seq
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    B, T, F, C = args.batch, args.seq_len, args.features, 3
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, T, F)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, size=(B, T))]

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam")
            .list()
            .layer(SelfAttentionLayer(n_out=32, n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=C, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(F, T))
            .build())
    net = MultiLayerNetwork(conf).init()

    n_dev = len(jax.devices())
    # largest divisor of the device count that fits the request — a
    # non-divisor degree would make data*seq != n_dev
    degree = max(d for d in range(1, min(args.seq_degree, n_dev) + 1)
                 if n_dev % d == 0)
    mesh = make_mesh(MeshConfig(data=n_dev // degree, seq=degree))
    print(f"mesh={dict(mesh.shape)} — time dim sharded {degree}-way")

    ds = DataSet(x, y)
    with seq.sequence_mesh(mesh):
        net.fit(ListDataSetIterator(ds, B))
        first = float(net.score())
        for _ in range(args.steps - 1):
            net.fit(ListDataSetIterator(ds, B))
        last = float(net.score())
    print(f"score {first:.4f} -> {last:.4f} over {args.steps} steps")


if __name__ == "__main__":
    main()
