"""LeNet on MNIST — the dl4j-examples ``LenetMnistExample`` recipe
(the BASELINE.md headline config) on this framework.

Run:  python examples/lenet_mnist.py [--epochs 2] [--platform cpu]

The whole train step (forward, loss, backward, updater) compiles into
ONE XLA program with donated buffers; on a TPU the MXU runs the conv
gemms in bf16.
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--examples", type=int, default=2048)
    ap.add_argument("--platform", default=None,
                    help="force a JAX backend, e.g. cpu")
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.nn.listeners import ScoreIterationListener

    net = lenet()
    net.set_listeners(ScoreIterationListener(10))
    train = MnistDataSetIterator(args.batch, train=True,
                                 num_examples=args.examples)
    test = MnistDataSetIterator(args.batch, train=False,
                                num_examples=max(256, args.examples // 4))
    net.fit(train, epochs=args.epochs)
    ev = net.evaluate(test)
    print(ev.stats())
    print(f"accuracy={ev.accuracy():.4f}")


if __name__ == "__main__":
    main()
