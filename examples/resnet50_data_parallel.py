"""ResNet-50 data-parallel over the device mesh — the BASELINE.md
ParallelWrapper north star: batch sharded over the ``data`` mesh axis,
params replicated, gradient psum inserted by XLA over ICI.

Run on real chips:   python examples/resnet50_data_parallel.py
Virtual 8-device CPU mesh (no TPU needed):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/resnet50_data_parallel.py --platform cpu --tiny
"""
import sys as _sys
from pathlib import Path as _Path

_sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--global-batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--tiny", action="store_true",
                    help="resnet18 at 32px, global batch 16, 2 steps")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.resnet import resnet18, resnet50
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    if args.tiny:
        net = resnet18(height=32, width=32, n_classes=10)
        args.global_batch, args.steps, args.image, classes = 16, 2, 32, 10
    else:
        net = resnet50(height=args.image, width=args.image)
        classes = 1000
    net.conf.global_conf.precision = "bf16"

    mesh = make_mesh()
    print(f"mesh={dict(mesh.shape)} devices={len(jax.devices())}")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.global_batch, 3, args.image,
                         args.image)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, args.global_batch)]
    batches = [DataSet(x, y) for _ in range(args.steps)]

    pw = ParallelWrapper(net, mesh)
    pw.fit(ListDataSetIterator(batches), epochs=1)
    print(f"trained {args.steps} steps, "
          f"score={float(net.score(DataSet(x, y))):.4f}")


if __name__ == "__main__":
    main()
